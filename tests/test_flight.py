"""Stall forensics + federated observability (ISSUE 16): the flight
recorder's phase ring and stall sentry, the probe heartbeat protocol
(including a forced hang that must land with a phase attribution and a
stack dump, never a bare timeout), the persistent XLA compilation
cache, the fed_forwarded / arbiter_reserve / arbiter_confirm spans on
job timelines, and the cluster-level SLO merge against the
single-controller oracle.

All tests run in the ``make tier1-flight`` lane (``-m flight``); they
are fast enough for tier-1 too (the two probe-subprocess tests pay one
jax import each).
"""

import json
import os
import socket
import sys
import time
import types

import pytest

from cranesched_tpu.ctld import (
    JobScheduler,
    JobSpec,
    MetaContainer,
    ResourceSpec,
    SchedulerConfig,
)
from cranesched_tpu.fed.arbiter import GangRequest
from cranesched_tpu.fed.shardmap import ShardMap, ShardSpec
from cranesched_tpu.fed.sim import FederatedCluster
from cranesched_tpu.obs import REGISTRY
from cranesched_tpu.obs.events import EventLog
from cranesched_tpu.obs.fedobs import (
    ClusterSlo,
    cluster_doc,
    merge_metric_snapshots,
)
from cranesched_tpu.obs.flight import (
    PROBE_PHASES,
    FlightRecorder,
    Heartbeat,
    dump_all_stacks,
    read_heartbeat,
)
from cranesched_tpu.obs.introspect import ProfilerWindow
from cranesched_tpu.obs.jobtrace import (
    FED_EDGES,
    SPAN_EDGES,
    JobTraceRecorder,
    render_waterfall,
)
from cranesched_tpu.obs.slo import SloEngine, SloSpec
from cranesched_tpu.rpc import crane_pb2 as pb, serve
from cranesched_tpu.rpc.client import CtldClient

pytestmark = pytest.mark.flight


# ---------------------------------------------------------------------------
# flight recorder: phase ring + stall sentry
# ---------------------------------------------------------------------------

def test_ring_is_bounded_and_report_tails():
    fr = FlightRecorder(capacity=16)
    for i in range(50):
        fr.stamp("phase", detail=str(i))
    rep = fr.report(tail=8)
    assert len(rep["phases"]) == 8
    # the ring kept only the newest capacity stamps
    assert rep["phases"][-1]["detail"] == "49"
    assert rep["phases"][0]["detail"] == "42"
    assert rep["stalls_total"] == 0
    assert rep["last_stall"] is None
    assert rep["armed"] is False
    assert rep["self_time_s"] >= 0.0
    fr.close()


def test_stall_sentry_fires_once_with_stacks_and_event():
    events = []
    fr = FlightRecorder(event_sink=lambda type, sev, detail="":
                        events.append((type, sev, detail)))
    fr.stamp("cycle_begin")
    fr.stamp("prelude")
    fr.arm(0.15, label="cycle")
    assert fr.report()["armed"] is True
    deadline = time.monotonic() + 5.0
    while fr.stalls_total == 0 and time.monotonic() < deadline:
        time.sleep(0.02)
    assert fr.stalls_total == 1
    stall = fr.report()["last_stall"]
    assert stall["label"] == "cycle"
    # the ring tail rode along: the last stamped phase is named
    assert [p["phase"] for p in stall["phases"]][-1] == "prelude"
    # every live thread's stack was captured — this test's main thread
    # must be among them, with real frames
    assert stall["stacks"]
    main = [k for k in stall["stacks"] if k.startswith("MainThread")]
    assert main and any("test_flight" in ln
                        for ln in stall["stacks"][main[0]])
    # the sentry fired ONCE and disarmed itself
    assert fr.report()["armed"] is False
    time.sleep(0.3)
    assert fr.stalls_total == 1
    assert events == [("flight_stall", "error",
                       "cycle stalled; last phase prelude; "
                       f"{len(stall['stacks'])} thread stacks captured")]
    fr.close()


def test_disarm_before_deadline_never_fires():
    fr = FlightRecorder()
    fr.arm(0.2, label="cycle")
    fr.disarm()
    time.sleep(0.4)
    assert fr.stalls_total == 0
    # re-arming after a disarm works (the cycle loop's steady state)
    fr.arm(30.0)
    assert fr.report()["armed"] is True
    fr.disarm()
    fr.close()


def test_dump_all_stacks_sees_this_thread():
    stacks = dump_all_stacks()
    me = [k for k in stacks if k.startswith("MainThread")]
    assert me
    assert any("dump_all_stacks" in ln or "test_flight" in ln
               for ln in stacks[me[0]])


# ---------------------------------------------------------------------------
# the probe heartbeat protocol
# ---------------------------------------------------------------------------

def test_heartbeat_roundtrip_and_torn_tail(tmp_path):
    path = str(tmp_path / "hb" / "heartbeat.jsonl")
    hb = Heartbeat(path)
    hb.stamp("jax_import")
    hb.stamp("backend_init", detail="cpu")
    hb.close()
    beats = read_heartbeat(path)
    assert [b["phase"] for b in beats] == ["jax_import", "backend_init"]
    assert beats[1]["detail"] == "cpu"
    assert beats[0]["t"] <= beats[1]["t"]
    # a probe killed mid-write leaves a torn last line: dropped, plus
    # blank lines and non-record JSON are skipped, never raised on
    with open(path, "a", encoding="utf-8") as fh:
        fh.write("\n42\n{\"t\": 17, \"pha")
    beats = read_heartbeat(path)
    assert [b["phase"] for b in beats] == ["jax_import", "backend_init"]
    # missing file is the probe-died-pre-stamp case
    assert read_heartbeat(str(tmp_path / "nope.jsonl")) == []


def test_probe_forced_hang_names_phase_and_captures_stack(
        tmp_path, monkeypatch):
    """The r06-r09 regression guard: a hung probe must produce a
    diagnosis naming the phase it hung in plus the child's faulthandler
    stack dump — never a bare timeout."""
    import bench
    monkeypatch.setenv("BENCH_PROBE_INJECT_HANG", "jax_import")
    monkeypatch.setenv("BENCH_XLA_CACHE_DIR", str(tmp_path / "xla"))
    res = bench._devices_with_timeout(8.0)
    assert res["acquired"] is False
    assert res["last_phase"] == "jax_import"
    assert res["phases"] == ["env_preflight", "jax_import"]
    assert "hung in phase 'jax_import'" in res["diagnosis"]
    assert "2/8 of the heartbeat protocol" in res["diagnosis"]
    # the env pre-flight report rides the diagnosis: on a real TPU
    # wedge it says WHY the plugin had a chance to hang
    assert "env pre-flight" in res["diagnosis"]
    assert "libtpu" in res["diagnosis"]
    assert res["preflight"]["chips"]["visible"] >= 0
    # SIGUSR1 harvested the wedged child's stacks before the kill: the
    # injected hang sleeps inside stamp(), which must be visible
    assert res["stacks"]
    assert "stamp" in res["stacks"]


def test_acquire_hang_hook_emits_backend_degraded_and_falls_back(
        tmp_path, monkeypatch):
    """The scheduler-boot half of the acquisition hardening: the
    BENCH_ACQUIRE_INJECT_HANG hook wedges the PJRT handshake, the
    bounded acquire must (a) attribute the phase, (b) emit a typed
    backend_degraded event through the sink, (c) leave the process
    forced to CPU — all within the budget."""
    from cranesched_tpu.parallel.acquire import (
        ACQUIRE_PHASES,
        acquire_backend,
    )
    monkeypatch.setenv("BENCH_ACQUIRE_INJECT_HANG", "backend_init")
    monkeypatch.delenv("BENCH_PROBE_INJECT_HANG", raising=False)
    monkeypatch.setenv("BENCH_XLA_CACHE_DIR", str(tmp_path / "xla"))
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    assert ACQUIRE_PHASES == PROBE_PHASES[:4]
    events = []
    t0 = time.monotonic()
    res = acquire_backend(8.0, warm=False,
                          event_sink=lambda type, sev, detail:
                          events.append((type, sev, detail)))
    assert time.monotonic() - t0 < 30.0  # budget + harvest grace
    assert res["acquired"] is False
    assert res["last_phase"] == "backend_init"
    assert "3/4 of the heartbeat protocol" in res["diagnosis"]
    assert [e[0] for e in events] == ["backend_degraded"]
    assert events[0][1] == "error"
    assert "backend_init" in events[0][2]
    # CPU fallback applied to THIS process
    assert os.environ["JAX_PLATFORMS"] == "cpu"
    # per-phase stamps for cflight: monotone times, named phases
    stamps = res["phase_stamps"]
    assert [s["phase"] for s in stamps] == res["phases"]
    assert all(a["t"] <= b["t"] for a, b in zip(stamps, stamps[1:]))


def test_ensure_backend_short_circuits_on_forced_cpu(monkeypatch):
    """With JAX_PLATFORMS=cpu pre-set the boot path must not pay a
    probe subprocess at all — just re-apply the config forcing."""
    from cranesched_tpu.parallel.acquire import ensure_backend
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    # a hang injection that would wedge any probe proves none ran
    monkeypatch.setenv("BENCH_ACQUIRE_INJECT_HANG", "env_preflight")
    t0 = time.monotonic()
    res = ensure_backend(timeout_s=60.0)
    assert time.monotonic() - t0 < 5.0
    assert res["acquired"] is True
    assert res["platform"] == "cpu"
    assert res["attempts"] == []
    assert "preflight" in res


def test_probe_happy_path_completes_protocol_and_warms_xla_cache(
        tmp_path, monkeypatch):
    """A healthy CPU probe walks all six phases; a second probe run
    against the same cache dir must land persistent-cache hits (the
    warm-compile contract that takes first_compile off the critical
    path across runs)."""
    import bench
    monkeypatch.delenv("BENCH_PROBE_INJECT_HANG", raising=False)
    monkeypatch.setenv("JAX_PLATFORMS", "cpu")
    cache_dir = str(tmp_path / "xla")
    monkeypatch.setenv("BENCH_XLA_CACHE_DIR", cache_dir)
    cold = bench._devices_with_timeout(120.0)
    assert cold["acquired"] is True, cold
    assert cold["phases"] == list(PROBE_PHASES)
    assert cold["platform"] == "cpu"
    xc = cold["xla_cache"]
    assert xc["enabled"] and not xc["error"]
    assert xc["entries"] >= 1  # the first compile was persisted
    warm = bench._devices_with_timeout(120.0)
    assert warm["acquired"] is True, warm
    assert warm["xla_cache"]["hits"] >= 1


def test_enable_xla_cache_counts_misses_in_subprocess(tmp_path):
    """enable_xla_cache + xla_cache_stats wiring, in a subprocess so
    the persistent cache config never leaks into this pytest process
    (it would mask recompiles other lanes assert on)."""
    import subprocess
    code = (
        "from cranesched_tpu.obs.flight import enable_xla_cache, "
        "xla_cache_stats\n"
        "import json, sys\n"
        "d = sys.argv[1]\n"
        "assert enable_xla_cache(d) and enable_xla_cache(d)\n"
        "import jax, jax.numpy as jnp\n"
        "jax.jit(lambda v: v * 3.0)(jnp.arange(8.0))\n"
        "print(json.dumps(xla_cache_stats()))\n")
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=os.path.dirname(os.path.dirname(
                   os.path.abspath(__file__))))
    out = subprocess.run(
        [sys.executable, "-c", code, str(tmp_path / "xla")],
        capture_output=True, text=True, timeout=120, env=env)
    assert out.returncode == 0, out.stderr
    st = json.loads(out.stdout.strip().splitlines()[-1])
    assert st["enabled"] and st["dir"] == str(tmp_path / "xla")
    assert st["misses"] >= 1 and st["entries"] >= 1
    assert st["hit_rate"] == 0.0  # cold dir: all misses


# ---------------------------------------------------------------------------
# federated spans: fed_forwarded + the arbiter pair
# ---------------------------------------------------------------------------

def test_fed_edges_stay_off_the_lifecycle_schema():
    # SPAN_EDGES is the happy-path contract other tests assert on; the
    # federation edges annotate timelines without joining it
    assert set(FED_EDGES).isdisjoint(SPAN_EDGES)
    assert FED_EDGES == ("fed_forwarded", "arbiter_reserve",
                        "arbiter_confirm")


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _shard_sched(name, partitions, nodes_per=2):
    meta = MetaContainer()
    nid = 0
    for part in partitions:
        for i in range(nodes_per):
            meta.add_node(f"{name}-{part}-n{i}",
                          meta.layout.encode(cpu=8.0,
                                             mem_bytes=16 << 30,
                                             memsw_bytes=16 << 30,
                                             is_capacity=True),
                          partitions=(part,))
            meta.craned_up(nid)
            nid += 1
    return JobScheduler(meta, SchedulerConfig(backfill=False))


def test_forwarded_submit_stamps_fed_forwarded_span():
    """A misrouted submit forwarded east->west leaves an unbroken
    waterfall on the owning shard: the fed_forwarded span carries the
    forwarding shard's send time and the receive-side skew."""
    ports = {"east": _free_port(), "west": _free_port()}
    shard_map = ShardMap([
        ShardSpec("east", ("batch",),
                  address=f"127.0.0.1:{ports['east']}"),
        ShardSpec("west", ("gpu",),
                  address=f"127.0.0.1:{ports['west']}"),
    ])
    servers = {}
    east = None
    try:
        for name in ("east", "west"):
            sched = _shard_sched(name, shard_map.partitions_of(name))
            server, bound = serve(sched, tick_mode=True,
                                  address=f"127.0.0.1:{ports[name]}",
                                  shard_name=name, shard_map=shard_map)
            assert bound == ports[name]
            servers[name] = server
        east = CtldClient(f"127.0.0.1:{ports['east']}")
        spec = pb.JobSpec(res=pb.ResourceSpec(cpu=1.0,
                                              mem_bytes=1 << 30,
                                              memsw_bytes=1 << 30),
                          sim_runtime=30.0, partition="gpu")
        reply = east.submit(spec)
        assert reply.shard == "west" and not reply.error
        doc = servers["west"].scheduler.jobtrace.timeline(reply.job_id)
        assert doc is not None
        spans = doc["incarnations"][0]["spans"]
        by_edge = {s["edge"]: s for s in spans}
        assert "fed_forwarded" in by_edge and "submit" in by_edge
        fwd = by_edge["fed_forwarded"]
        # send-time stamp + receive-side skew, never a broken timeline
        assert fwd["t"] <= by_edge["submit"]["t"] + 1e-6
        assert fwd["skew"] >= 0.0
        # a local submit never gains the span
        local = east.submit(pb.JobSpec(
            res=pb.ResourceSpec(cpu=1.0, mem_bytes=1 << 30,
                                memsw_bytes=1 << 30),
            sim_runtime=30.0, partition="batch"))
        ldoc = servers["east"].scheduler.jobtrace.timeline(
            local.job_id)
        ledges = {s["edge"]
                  for s in ldoc["incarnations"][0]["spans"]}
        assert "fed_forwarded" not in ledges
        # the waterfall renderer takes fed spans in stride
        text = "\n".join(render_waterfall(doc))
        assert "fed_forwarded" in text
    finally:
        if east is not None:
            east.close()
        for server in servers.values():
            server.stop()


def test_arbiter_gang_spans_reserve_confirm_placed(tmp_path):
    """Every gang member's timeline shows the two-phase commit:
    arbiter_reserve (lease grant) -> arbiter_confirm (commit, carrying
    the fencing epoch) -> placed, in time order, on each shard."""
    fc = FederatedCluster({"east": {"batch": 2}, "west": {"gpu": 2}},
                          wal_dir=str(tmp_path))
    fc.submit_gang(GangRequest(
        name="g1", node_num=4, partitions=("batch", "gpu"),
        spec=JobSpec(user="u",
                     res=ResourceSpec(cpu=1.0, mem_bytes=1 << 30,
                                      memsw_bytes=1 << 30),
                     sim_runtime=5.0)))
    fc.run_until_drained()
    assert fc.arbiter.stats["commits"] == 1
    seen = 0
    for shard in fc.shards.values():
        for job in shard.scheduler.history.values():
            if not job.spec.name.startswith("g1@"):
                continue
            seen += 1
            doc = shard.scheduler.jobtrace.timeline(job.job_id)
            assert doc is not None, job.spec.name
            spans = doc["incarnations"][0]["spans"]
            by_edge = {s["edge"]: s for s in spans}
            for edge in ("arbiter_reserve", "arbiter_confirm",
                         "placed"):
                assert edge in by_edge, (job.spec.name, sorted(by_edge))
            assert (by_edge["arbiter_reserve"]["t"]
                    <= by_edge["arbiter_confirm"]["t"]
                    <= by_edge["placed"]["t"])
            # the confirm span carries the shard's fencing epoch
            assert (doc["incarnations"][0]["fencing_epoch"]
                    == shard.scheduler.fencing_epoch)
    assert seen == 2  # one member per partition


# ---------------------------------------------------------------------------
# cluster-level SLO merge vs the single-controller oracle
# ---------------------------------------------------------------------------

def _spec(name, windows=(3600.0,)):
    return SloSpec(name, "submit", "dispatched", 99.0, 0.5, windows)


def _feed(engine_or_recorders, samples, base):
    """Stamp (job_id, latency) samples through a recorder so the SLO
    engine sees them exactly as production does."""
    rec, jobs = engine_or_recorders
    for job_id, lat in samples:
        t0 = base + job_id * 1e-3
        rec.stamp(job_id, 0, "submit", t0)
        rec.stamp(job_id, 0, "dispatched", t0 + lat)


def test_two_shard_burn_merge_matches_single_controller_oracle():
    base = 1_000_000.0
    now = base + 100.0
    # 13/200 samples over the 0.5 s target; p99 allows 1% -> burn 6.5
    lats = [2.0 if i % 16 == 0 else 0.05 for i in range(200)]
    oracle = SloEngine([_spec("e2e-oracle")])
    ora_rec = JobTraceRecorder(capacity=1024, slo=oracle)
    _feed((ora_rec, None), list(enumerate(lats)), base)
    ora_row = oracle.evaluate(now)[0]

    shard_rows = {}
    for shard, beg in (("east", 0), ("west", 1)):
        eng = SloEngine([_spec("e2e-oracle")])
        rec = JobTraceRecorder(capacity=1024, slo=eng)
        _feed((rec, None),
              [(i, lats[i]) for i in range(beg, 200, 2)], base)
        shard_rows[shard] = eng.evaluate(now)
    clu = ClusterSlo().merge(shard_rows)
    assert len(clu) == 1
    row = clu[0]
    assert row["shards"] == ["east", "west"]
    for wk, win in ora_row["windows"].items():
        cwin = row["windows"][wk]
        assert cwin["count"] == win["count"] == 200
        assert cwin["shard_counts"] == {"east": 100, "west": 100}
        # the exact-merge contract: cluster burn == what one controller
        # holding every sample computes
        assert cwin["burn_rate"] == pytest.approx(
            win["burn_rate"], abs=1e-3)
        assert cwin["breaching"] == win["breaching"]
        # percentiles don't merge exactly: max over shards, flagged
        assert cwin["observed_is_max_over_shards"] is True
        assert cwin["observed"] >= win["observed"] - 1e-9


def test_cluster_breach_counter_edge_triggers_once_per_onset():
    name = "flight-breach-edge"
    breaches = REGISTRY.counter("crane_fed_slo_breaches_total")
    before = breaches.value(slo=name)

    def table(burn):
        return {"s1": [{"name": name, "from": "submit",
                        "to": "dispatched", "p": 99.0,
                        "target_seconds": 0.5,
                        "windows": {"60": {
                            "count": 100, "observed": 1.0,
                            "burn_rate": burn,
                            "breaching": burn >= 1.0}}}]}

    clu = ClusterSlo()
    assert clu.merge(table(2.0))[0]["windows"]["60"]["breaching"]
    assert breaches.value(slo=name) == before + 1
    clu.merge(table(3.0))  # still burning: no second bump
    assert breaches.value(slo=name) == before + 1
    assert not clu.merge(table(0.0))[0]["windows"]["60"]["breaching"]
    clu.merge(table(2.0))  # a fresh onset counts again
    assert breaches.value(slo=name) == before + 2
    # the cluster burn gauge tracked the latest merge
    assert REGISTRY.gauge("crane_fed_slo_burn_rate").value(
        slo=name, window="60") == pytest.approx(2.0, abs=1e-3)


def test_merge_metric_snapshots_by_kind():
    snaps = {
        "east": {
            "crane_jobs_total": {"type": "counter",
                                 "values": {"{}": 5.0}},
            "crane_lat": {"type": "histogram",
                          "values": {'{edge="submit"}':
                                     {"count": 4, "sum": 2.0}}},
            "crane_queue_depth": {"type": "gauge",
                                  "values": {"{}": 7.0}},
        },
        "west": {
            "crane_jobs_total": {"type": "counter",
                                 "values": {"{}": 3.0}},
            "crane_lat": {"type": "histogram",
                          "values": {'{edge="submit"}':
                                     {"count": 1, "sum": 0.5}}},
            "crane_queue_depth": {"type": "gauge",
                                  "values": {'{part="gpu"}': 2.0}},
        },
    }
    out = merge_metric_snapshots(snaps)
    # counters and histograms are extensive: summed per label set
    assert out["crane_jobs_total"]["values"] == {"{}": 8.0}
    assert out["crane_lat"]["values"] == {
        '{edge="submit"}': {"count": 5, "sum": 2.5}}
    # gauges are not: one row per shard, shard= label prefixed
    assert out["crane_queue_depth"]["values"] == {
        '{shard="east"}': 7.0,
        '{shard="west",part="gpu"}': 2.0}


def test_cluster_doc_staleness_and_degraded_shards():
    now = 5_000.0
    good = types.SimpleNamespace(
        json=json.dumps({
            "watchdog": {"now": now - 4.0},
            "slo": [{"name": "e2e", "from": "submit",
                     "to": "dispatched", "p": 99.0,
                     "target_seconds": 0.5,
                     "windows": {"60": {"count": 10, "observed": 0.1,
                                        "burn_rate": 0.0,
                                        "breaching": False}}}],
            "metrics": {"crane_jobs_total": {
                "type": "counter", "values": {"{}": 2.0}}},
            "flight": {"stalls_total": 1, "last_stall": None},
        }),
        durable_seq=7)
    bad = types.SimpleNamespace(json="not json{", durable_seq=0)
    fanout = types.SimpleNamespace(
        replies={"east": good, "bad": bad}, errors={"west": "down"})
    doc = cluster_doc(fanout, now=now, max_staleness=1.5)
    assert doc["max_staleness"] == 1.5
    east = doc["shards"]["east"]
    assert east["durable_seq"] == 7
    assert east["staleness_s"] == pytest.approx(4.0, abs=0.01)
    assert east["flight"]["stalls_total"] == 1
    # the dead shard and the garbled one degrade, never block
    assert doc["errors"]["west"] == "down"
    assert doc["errors"]["bad"] == "unparseable stats reply"
    assert "bad" not in doc["shards"]
    assert doc["slo"][0]["name"] == "e2e"
    assert doc["slo"][0]["windows"]["60"]["count"] == 10
    assert doc["metrics"]["crane_jobs_total"]["values"] == {"{}": 2.0}


# ---------------------------------------------------------------------------
# satellite 3: promotion re-seed — synthetic spans never feed the
# cluster SLO windows; the follower's event log re-seeds via ingest
# ---------------------------------------------------------------------------

def _recovered_job(job_id, submit_t, start_t):
    return types.SimpleNamespace(
        job_id=job_id, requeue_count=0, submit_time=submit_t,
        start_time=start_t,
        status=types.SimpleNamespace(is_terminal=False),
        end_time=None)


def test_promotion_reseed_excludes_synthetic_spans_from_cluster_slo():
    """A promoted standby re-seeds its jobtrace with synthetic
    back-dated spans (jobtrace.seed_recovered).  Those spans would read
    as huge submit->dispatched latencies; they must never enter the SLO
    windows — per-shard or cluster-merged — while post-promotion REAL
    spans still do."""
    base = 2_000_000.0
    now = base + 50.0
    # shard A: a healthy leader with real samples
    eng_a = SloEngine([_spec("promo-e2e")])
    rec_a = JobTraceRecorder(capacity=256, slo=eng_a)
    _feed((rec_a, None), [(i, 0.1) for i in range(20)], base)
    # shard B: a standby promoted mid-run, re-adopting started jobs
    eng_b = SloEngine([_spec("promo-e2e")])
    rec_b = JobTraceRecorder(capacity=256, slo=eng_b)
    for jid in range(100, 110):
        rec_b.seed_recovered(
            _recovered_job(jid, base - 3600.0, base - 1800.0), now)
    tl = rec_b.timeline(100)["incarnations"][0]
    assert {s["edge"] for s in tl["spans"]} >= {
        "submit", "eligible", "placed", "dispatched"}
    assert all(s.get("synthetic") for s in tl["spans"])
    row_b = eng_b.evaluate(now)[0]
    assert all(w["count"] == 0 for w in row_b["windows"].values())
    # a REAL post-promotion span on the promoted shard still counts
    rec_b.stamp(999, 0, "submit", now - 1.0)
    rec_b.stamp(999, 0, "dispatched", now - 0.9)
    row_b = eng_b.evaluate(now)[0]
    row_a = eng_a.evaluate(now)[0]
    clu = ClusterSlo().merge({"a": [row_a], "b": [row_b]})
    for wk, win in clu[0]["windows"].items():
        assert win["count"] == row_a["windows"][wk]["count"] + 1
        assert win["shard_counts"]["b"] == 1
        assert not win["breaching"]


def test_follower_event_log_reseeds_via_ingest():
    """The promotion path's event-log half: the follower ingests the
    leader's replicated events (cursor on the leader seq, duplicates
    dropped) and keeps emitting monotonically after promotion."""
    leader = EventLog()
    leader.emit("leader_elected", detail="epoch 3")
    leader.emit_node_transition("down", "n0", now=10.0)
    leader.emit("flight_stall", severity="error", detail="cycle wedged")
    records = leader.since()
    follower = EventLog()
    assert all(follower.ingest(r) for r in records)
    # at-least-once fetch: the duplicate batch is dropped wholesale
    assert not any(follower.ingest(r) for r in records)
    assert follower.remote_seq == records[-1]["seq"]
    got = follower.since()
    assert [r["type"] for r in got] == [
        "leader_elected", "node_down", "flight_stall"]
    assert [r["severity"] for r in got] == ["info", "warning", "error"]
    # post-promotion emissions stay monotone past the ingested seqs
    promoted = follower.emit("leader_elected", detail="epoch 4")
    assert promoted["seq"] > got[-1]["seq"]


# ---------------------------------------------------------------------------
# satellite 2: profiler capture dirs are shard-namespaced
# ---------------------------------------------------------------------------

def test_profiler_capture_dirs_never_collide_across_shards(tmp_path):
    """Two federated shards sharing one filesystem arm a capture in
    the same instant: the shard namespace (possibly learned late, via a
    callable) plus the per-process sequence keep the dirs distinct."""
    east = ProfilerWindow(base_dir=str(tmp_path), namespace="east")
    west = ProfilerWindow(base_dir=str(tmp_path),
                          namespace=lambda: "west")
    ok1, d1 = east.request(1)
    ok2, d2 = west.request(1)
    assert ok1 and ok2
    assert d1 != d2
    assert "capture-east-" in d1 and "capture-west-" in d2
    # same shard, back-to-back arms in the same millisecond: the
    # capture sequence still uniquifies
    east._armed = 0
    east._active_dir = ""
    ok3, d3 = east.request(1)
    assert ok3 and d3 != d1
    # a namespace callable that blows up degrades to the bare tag
    weird = ProfilerWindow(base_dir=str(tmp_path),
                           namespace=lambda: 1 / 0)
    ok4, d4 = weird.request(1)
    assert ok4 and "capture-" in d4 and "capture--" not in d4


# ---------------------------------------------------------------------------
# cflight: the forensics viewer
# ---------------------------------------------------------------------------

def test_cflight_renders_bench_probe_diagnosis(tmp_path, capsys):
    from cranesched_tpu.cli import cmd_cflight
    doc = {"device_acquisition": {
        "acquired": False,
        "phases": ["jax_import", "backend_init", "first_trace"],
        "diagnosis": "the TPU probe hung in phase 'first_trace'",
        "stacks": "Thread 0x01 (most recent call first):\n  ...",
    }}
    path = tmp_path / "BENCH_r10.json"
    path.write_text(json.dumps(doc))
    args = types.SimpleNamespace(file=str(path), tail=32)
    assert cmd_cflight(args) == 1  # not acquired -> nonzero for drills
    out = capsys.readouterr().out
    assert "jax_import->backend_init->first_trace" in out
    assert "hung in phase 'first_trace'" in out
    assert "harvested probe stacks" in out
    # a healthy probe exits 0
    ok = {"device_acquisition": {"acquired": True,
                                 "phases": list(PROBE_PHASES)}}
    path.write_text(json.dumps(ok))
    assert cmd_cflight(args) == 0
    # the committed BENCH_rNN.json wrapper nests the bench doc under
    # "parsed" — cflight digs the probe outcome out of it too
    wrapper = {"n": 10, "cmd": "python bench.py", "rc": 0,
               "parsed": {"detail": doc}}
    path.write_text(json.dumps(wrapper))
    capsys.readouterr()
    assert cmd_cflight(args) == 1
    assert "hung in phase 'first_trace'" in capsys.readouterr().out


def test_cflight_renders_acquisition_phase_stamps(tmp_path, capsys):
    """ISSUE 17: the acquisition handshake's heartbeat stamps render
    as a relative timeline, so the gap after the last stamp names the
    wedged phase at a glance."""
    from cranesched_tpu.cli import cmd_cflight
    doc = {"device_acquisition": {
        "acquired": False,
        "phases": ["env_preflight", "jax_import", "backend_init"],
        "phase_stamps": [
            {"phase": "env_preflight", "t": 100.0},
            {"phase": "jax_import", "t": 100.25},
            {"phase": "backend_init", "t": 101.5},
        ],
        "diagnosis": "wedged in backend_init",
    }}
    path = tmp_path / "BENCH_r11.json"
    path.write_text(json.dumps(doc))
    args = types.SimpleNamespace(file=str(path), tail=32)
    assert cmd_cflight(args) == 1
    out = capsys.readouterr().out
    assert "stamp env_preflight" in out and "+0.000s" in out
    assert "stamp jax_import" in out and "+0.250s" in out
    assert "stamp backend_init" in out and "+1.500s" in out


def test_cflight_renders_live_stall(capsys):
    from cranesched_tpu.cli import _render_flight
    fr = FlightRecorder()
    fr.stamp("cycle_begin")
    fr.arm(0.05, label="cycle")
    deadline = time.monotonic() + 5.0
    while fr.stalls_total == 0 and time.monotonic() < deadline:
        time.sleep(0.02)
    text = "\n".join(_render_flight(fr.report()))
    assert "cycle_begin" in text
    assert "LAST STALL label='cycle'" in text
    assert "-- thread MainThread" in text
    fr.close()
