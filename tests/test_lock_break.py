"""The ctld lock must NOT be held across the solve (VERDICT r5 #4).

The cycle runs as ``cycle_phases``: state phases under the server
lock, each yielded solve closure with the lock released
(rpc/server.py::_cycle_loop).  These tests inject an artificially slow
solve and prove that (a) submits and queries landing mid-solve return
in milliseconds instead of waiting out the solve (the reference
reaches the same property with 9 scheduler threads + per-entry locks,
JobScheduler.h:1290-1335), and (b) mutations that land mid-solve —
cancel, node death — are honored by the commit revalidation
(_commit's pending/held guard + the ResReduceEvent window,
JobScheduler.cpp:1437-1540)."""

import threading
import time

import numpy as np
import pytest

from cranesched_tpu.craned import SimCluster
from cranesched_tpu.ctld import (
    JobScheduler,
    JobStatus,
    JobSpec,
    MetaContainer,
    ResourceSpec,
    SchedulerConfig,
)
from cranesched_tpu.rpc import crane_pb2 as pb
from cranesched_tpu.rpc.client import CtldClient
from cranesched_tpu.rpc.server import serve


def _cluster(num_nodes=8, solve_delay=0.0):
    meta = MetaContainer()
    for i in range(num_nodes):
        meta.add_node(
            f"cn{i:02d}",
            meta.layout.encode(cpu=16, mem_bytes=32 << 30,
                               memsw_bytes=32 << 30, is_capacity=True),
            partitions=("default",))
        meta.craned_up(i)
    sched = JobScheduler(meta, SchedulerConfig(backfill=False))
    cluster = SimCluster(sched)
    sched.dispatch = cluster.dispatch
    sched.dispatch_terminate = cluster.terminate
    if solve_delay:
        # wrap the immediate solver with a sleep INSIDE the yielded
        # closure — i.e. inside the window where _cycle_loop has
        # dropped the lock.  This models a big (1 s-class) solve
        # without needing 50k jobs in a unit test.
        inner = sched._immediate_solve

        def slow(*a, **kw):
            time.sleep(solve_delay)
            return inner(*a, **kw)

        sched._immediate_solve = slow
    return meta, sched, cluster


def _pbspec(cpu=1.0, runtime=30.0):
    return pb.JobSpec(
        res=pb.ResourceSpec(cpu=cpu, mem_bytes=1 << 30,
                            memsw_bytes=1 << 30),
        time_limit=3600, partition="default", user="alice",
        sim_runtime=runtime)


def test_submit_and_query_latency_during_slow_cycle():
    meta, sched, cluster = _cluster(solve_delay=1.0)
    server, port = serve(sched, sim=cluster, address="127.0.0.1:0",
                         cycle_interval=0.1)
    client = CtldClient(f"127.0.0.1:{port}")
    try:
        # seed pending work so every cycle actually solves
        for _ in range(4):
            client.submit(_pbspec())
        deadline = time.time() + 3.0
        lat = []
        while time.time() < deadline:
            t0 = time.perf_counter()
            client.submit(_pbspec())
            client.query_jobs()
            lat.append(time.perf_counter() - t0)
        lat.sort()
        p99 = lat[int(len(lat) * 0.99) - 1]
        # >=2 one-second solves ran inside this window; with the lock
        # held across solves p99 would be ~1 s (REPLAY_r04 measured
        # 1.5 s max).  50 ms is the VERDICT r5 #4 budget.
        assert p99 < 0.05, f"submit+query p99 {p99 * 1e3:.1f} ms"
        assert len(lat) > 50  # the client genuinely ran during solves
    finally:
        server.stop()


def test_cycle_still_places_during_concurrent_submits():
    meta, sched, cluster = _cluster(solve_delay=0.2)
    server, port = serve(sched, sim=cluster, address="127.0.0.1:0",
                         cycle_interval=0.05)
    client = CtldClient(f"127.0.0.1:{port}")
    try:
        ids = [client.submit(_pbspec()).job_id for _ in range(12)]
        deadline = time.time() + 8.0
        while time.time() < deadline:
            infos = client.query_jobs(job_ids=ids).jobs
            if sum(1 for j in infos
                   if j.status == "Running") >= 8:
                break
            time.sleep(0.05)
        infos = client.query_jobs(job_ids=ids).jobs
        running = [j for j in infos if j.status == "Running"]
        assert len(running) >= 8, [j.status for j in infos]
    finally:
        server.stop()


def test_cancel_mid_solve_voids_placement():
    """A job canceled while the solve runs must not start: _commit's
    pending-membership guard discards the stale placement."""
    meta, sched, cluster = _cluster(solve_delay=0.0)
    jid = sched.submit(_spec_native(), now=0.0)

    gen = sched.cycle_phases(now=1.0)
    fn = next(gen)          # prelude + snapshot done, solve pending
    sched.cancel(jid, now=1.0)     # lands "mid-solve"
    result = fn()
    with pytest.raises(StopIteration) as stop:
        while True:
            fn = gen.send(result)
            result = fn()
    assert stop.value.value == []  # nothing started
    job = sched.job_info(jid)
    assert job.status == JobStatus.CANCELLED
    # no resources leaked
    for node in meta.nodes.values():
        assert (node.avail == node.total).all()


def test_modify_mid_solve_voids_placement():
    """A partition move landing mid-solve must void the placement
    computed against the OLD partition (spec-epoch guard in _commit)."""
    meta, sched, cluster = _cluster(solve_delay=0.0)
    jid = sched.submit(_spec_native(), now=0.0)

    gen = sched.cycle_phases(now=1.0)
    fn = next(gen)
    result = fn()           # solve placed it in "default"
    err = sched.modify_job(jid, now=1.0, partition="default")
    assert err == ""        # spec replaced (same name, new object)
    with pytest.raises(StopIteration) as stop:
        while True:
            fn = gen.send(result)
            result = fn()
    assert stop.value.value == []
    assert sched.job_info(jid).status == JobStatus.PENDING
    # next cycle (fresh spec) places it normally
    assert sched.schedule_cycle(now=2.0) == [jid]


def test_node_death_mid_solve_revalidated():
    """All nodes die mid-solve: ResReduceEvents void every placement
    (the reference's validation at JobScheduler.cpp:1466-1540)."""
    meta, sched, cluster = _cluster(solve_delay=0.0, num_nodes=2)
    jid = sched.submit(_spec_native(), now=0.0)

    gen = sched.cycle_phases(now=1.0)
    fn = next(gen)
    result = fn()           # solve picked a node
    for nid in list(meta.nodes):
        meta.craned_down(nid)      # mid-cycle reduce events
    with pytest.raises(StopIteration) as stop:
        while True:
            fn = gen.send(result)
            result = fn()
    assert stop.value.value == []
    job = sched.job_info(jid)
    assert job.status == JobStatus.PENDING


def _spec_native(cpu=1.0):
    return JobSpec(res=ResourceSpec(cpu=cpu, mem_bytes=1 << 30,
                                    memsw_bytes=1 << 30),
                   sim_runtime=30.0)
