"""AuthN/AuthZ over the RPC surface.

Reference: every external RPC verifies a per-user mTLS certificate
against the claimed uid (CheckCertAndUIDAllowed_, CtldGrpcServer.h:568,
used at :698+) before RBAC.  Here the minimum viable equivalent: ctld-
issued bearer tokens in gRPC metadata, owner-or-admin on job mutations,
authenticated accounting actor, and a cluster secret for the
craned-internal surface.  Acceptance bar (VERDICT r2 #6): a cross-user
cancel is refused.
"""

import pytest

from cranesched_tpu.craned.sim import SimCluster
from cranesched_tpu.ctld import (
    JobScheduler,
    MetaContainer,
    SchedulerConfig,
)
from cranesched_tpu.ctld.auth import AuthManager
from cranesched_tpu.rpc import CtldClient, crane_pb2 as pb, serve


@pytest.fixture()
def secured(tmp_path):
    meta = MetaContainer()
    for i in range(2):
        meta.add_node(f"cn{i}", meta.layout.encode(
            cpu=8, mem_bytes=16 << 30, memsw_bytes=16 << 30,
            is_capacity=True))
        meta.craned_up(i)
    sched = JobScheduler(meta, SchedulerConfig(backfill=False))
    sim = SimCluster(sched)
    sim.wire(sched)
    auth = AuthManager(str(tmp_path / "tokens.json"))
    server, port = serve(sched, sim=sim, tick_mode=True, auth=auth)
    addr = f"127.0.0.1:{port}"
    root = CtldClient(addr, token=auth.root_token)
    clients = [root]

    def client_for(user):
        token = root.issue_token(user).token
        c = CtldClient(addr, token=token)
        clients.append(c)
        return c

    yield sched, auth, root, client_for, addr
    for c in clients:
        c.close()
    server.stop()


def spec(user, runtime=100.0):
    return pb.JobSpec(user=user,
                      res=pb.ResourceSpec(cpu=1.0, mem_bytes=1 << 30),
                      sim_runtime=runtime)


def test_unauthenticated_requests_refused(secured, tmp_path):
    import grpc

    sched, auth, root, client_for, addr = secured
    anon = CtldClient(addr)
    try:
        r = anon.submit(spec("anyone"))
        assert r.job_id == 0 and "authentication required" in r.error
        assert not anon.cancel(1).ok
        assert not anon.acct_mgr("root", "show").ok
        # the read surface is closed too (information disclosure):
        # queries abort UNAUTHENTICATED rather than leaking the queue
        for call in (lambda: anon.query_jobs(include_history=True),
                     lambda: anon.query_cluster(),
                     lambda: anon.query_steps(1),
                     lambda: anon.query_stats()):
            try:
                call()
                raise AssertionError("anonymous query succeeded")
            except grpc.RpcError as exc:
                assert exc.code() == grpc.StatusCode.UNAUTHENTICATED
        # Tick denial is explicit, never a silent empty cycle
        assert "permission" in anon.tick(1.0).error             or "authentication" in anon.tick(1.0).error
    finally:
        anon.close()


def test_cross_user_cancel_refused(secured):
    sched, auth, root, client_for, addr = secured
    alice = client_for("alice")
    mallory = client_for("mallory")
    jid = alice.submit(spec("alice")).job_id
    assert jid > 0
    root.tick(0.0)
    # mallory cannot touch alice's job — the acceptance bar
    r = mallory.cancel(jid)
    assert not r.ok and "permission denied" in r.error
    assert not mallory.suspend(jid).ok
    assert not mallory.hold(jid).ok
    assert sched.job_info(jid).status.value == "Running"
    # alice can; root (admin) also can
    assert alice.suspend(jid).ok
    assert root.resume(jid).ok
    assert alice.cancel(jid).ok


def test_submit_identity_must_match_spec_user(secured):
    sched, auth, root, client_for, addr = secured
    alice = client_for("alice")
    r = alice.submit(spec("bob"))       # claiming someone else
    assert r.job_id == 0 and "permission denied" in r.error
    assert root.submit(spec("bob")).job_id > 0   # admin may act for bob


def test_acctmgr_actor_is_authenticated_identity(secured):
    sched, auth, root, client_for, addr = secured
    from cranesched_tpu.ctld.accounting import AccountManager, User, \
        AdminLevel
    sched.accounts = AccountManager()
    sched.accounts.users["root"] = User(name="root",
                                        admin_level=AdminLevel.ROOT)
    alice = client_for("alice")
    # the request CLAIMS root but the authenticated identity is alice:
    # the privileged mutation must be refused
    r = alice.acct_mgr("root", "add_qos", {"name": "q", "priority": 5})
    assert not r.ok and "permission" in r.error
    assert root.acct_mgr("ignored-claim", "add_qos",
                         {"name": "q", "priority": 5}).ok
    assert "q" in sched.accounts.qos


def test_steps_and_allocation_ownership(secured):
    sched, auth, root, client_for, addr = secured
    alice = client_for("alice")
    mallory = client_for("mallory")
    jid = alice.submit(pb.JobSpec(
        user="alice", res=pb.ResourceSpec(cpu=4.0, mem_bytes=1 << 30),
        alloc_only=True, time_limit=600)).job_id
    root.tick(0.0)
    assert not mallory.submit_step(
        jid, pb.StepSpec(name="x", sim_runtime=5.0)).step_id >= 0
    assert not mallory.free_allocation(jid).ok
    sid = alice.submit_step(jid, pb.StepSpec(
        name="mine", sim_runtime=5.0)).step_id
    assert sid == 0
    assert not mallory.cancel_step(jid, sid).ok
    assert alice.cancel_step(jid, sid).ok
    assert alice.free_allocation(jid).ok


def test_admin_only_surfaces(secured):
    sched, auth, root, client_for, addr = secured
    alice = client_for("alice")
    assert not alice.create_reservation("r", "default", ["cn0"],
                                        0.0, 100.0).ok
    assert not alice.modify_node("cn0", "drain").ok
    assert not alice.issue_token("eve").ok
    assert root.create_reservation("r", "default", ["cn0"],
                                   0.0, 100.0).ok
    assert root.modify_node("cn0", "drain").ok


def test_craned_internal_needs_cluster_secret(secured):
    sched, auth, root, client_for, addr = secured
    alice = client_for("alice")
    total = pb.ResourceSpec(cpu=4.0, mem_bytes=8 << 30)
    assert not alice.craned_register("evil", total).ok
    craned = CtldClient(addr, token=auth.craned_token)
    try:
        reply = craned.craned_register("cn99", total)
        assert reply.ok
        assert craned.craned_ping(reply.node_id).ok
    finally:
        craned.close()


def test_revoked_token_stops_working(secured):
    sched, auth, root, client_for, addr = secured
    alice = client_for("alice")
    jid = alice.submit(spec("alice")).job_id
    assert jid > 0
    assert root.revoke_token("alice").ok
    r = alice.submit(spec("alice"))
    assert r.job_id == 0 and "authentication required" in r.error


def test_tokens_persist_across_restart(tmp_path):
    path = str(tmp_path / "tok.json")
    a1 = AuthManager(path)
    t = a1.issue("root", "alice")
    a2 = AuthManager(path)                 # restart
    assert a2.identity((("crane-token", t),)) == "alice"
    assert a2.root_token == a1.root_token
    assert a2.craned_token == a1.craned_token


def test_per_node_craned_token_is_bound_to_its_node(secured):
    """ADVICE r3: a per-node token (@craned/<name>) must not be able to
    impersonate other nodes on the internal surface."""
    sched, auth, root, client_for, addr = secured
    t0 = auth.issue_craned("root", "cn0")
    cn0 = CtldClient(addr, token=t0)
    try:
        # registering as its own name works, as another name is refused
        total = pb.ResourceSpec(cpu=4.0, mem_bytes=8 << 30)
        assert not cn0.craned_register("cn1", total).ok
        r = cn0.craned_register("cn0", total)
        assert r.ok
        assert cn0.craned_ping(r.node_id).ok          # own node_id: ok
        other = sched.meta.node_by_name("cn1").node_id
        assert not cn0.craned_ping(other).ok          # foreign: denied
    finally:
        cn0.close()


def test_token_table_stores_hashes_not_plaintext(tmp_path):
    """ADVICE r3: a leaked table file must not contain usable tokens."""
    import json as _json
    path = str(tmp_path / "tok.json")
    a = AuthManager(path)
    t = a.issue("root", "alice")
    with open(path, encoding="utf-8") as fh:
        table = _json.load(fh)
    assert t not in table                     # no plaintext row
    assert a.root_token not in table
    assert all(len(k) == 64 for k in table)   # sha256 hex keys only
    # and the hashes still authenticate
    assert a.identity((("crane-token", t),)) == "alice"


def test_legacy_plaintext_table_migrates_to_hashes(tmp_path):
    import json as _json
    path = str(tmp_path / "tok.json")
    with open(path, "w", encoding="utf-8") as fh:
        _json.dump({"OLDROOT": "root", "OLDSECRET": "@craned",
                    "ALICETOK": "alice"}, fh)
    a = AuthManager(path)
    assert a.root_token == "OLDROOT"          # daemon creds recovered
    assert a.craned_token == "OLDSECRET"
    assert a.identity((("crane-token", "ALICETOK"),)) == "alice"
    with open(path, encoding="utf-8") as fh:
        table = _json.load(fh)
    assert "ALICETOK" not in table            # rewritten as hash


def test_node_bound_token_cannot_use_unresolvable_node_id(secured):
    """Fail closed: a per-node token sending an unknown or -1 node_id
    (the whole-job report form) must be denied, not skipped past the
    binding check."""
    sched, auth, root, client_for, addr = secured
    t0 = auth.issue_craned("root", "cn0")
    cn0 = CtldClient(addr, token=t0)
    try:
        assert not cn0.craned_ping(999).ok       # unknown node id
        r = cn0.step_status_change(1, "FAILED", 1, 0.0, node_id=-1)
        assert not r.ok and "bound to node" in r.error
    finally:
        cn0.close()


def test_revoking_bootstrap_identity_rotates_keyring(tmp_path):
    """Revoking '@craned' must survive a restart: the keyring credential
    rotates, so the old secret cannot resurrect via bootstrap."""
    path = str(tmp_path / "tok.json")
    a1 = AuthManager(path)
    old = a1.craned_token
    assert a1.revoke("root", "@craned") >= 1
    assert a1.identity((("crane-token", old),)) is None
    assert a1.craned_token != old                 # rotated in-session
    a2 = AuthManager(path)                        # restart
    assert a2.identity((("crane-token", old),)) is None
    assert a2.craned_token == a1.craned_token


def test_legacy_migration_persists_keyring_across_two_restarts(tmp_path):
    import json as _json
    path = str(tmp_path / "tok.json")
    with open(path, "w", encoding="utf-8") as fh:
        _json.dump({"OLDROOT": "root", "OLDSECRET": "@craned"}, fh)
    a1 = AuthManager(path)                        # migration restart
    a2 = AuthManager(path)                        # second restart
    assert a2.root_token == "OLDROOT"             # not silently rotated
    assert a2.craned_token == "OLDSECRET"
