"""Placement parity: TPU solver vs NumPy oracle on randomized clusters.

This is the golden-trace strategy SURVEY.md §4 calls for: the reference repo
has no distributed test harness, so correctness of the device solve is
established differentially against an obviously-correct host oracle.
"""

import numpy as np
import jax.numpy as jnp
import pytest

from cranesched_tpu.models.solver import (
    make_cluster_state,
    JobBatch,
    solve_greedy,
    REASON_NONE,
)
from cranesched_tpu.ops.resources import ResourceLayout
from cranesched_tpu.testing.oracle import solve_greedy_oracle


def random_problem(rng, n_jobs, n_nodes, n_parts=1, max_nodes=1,
                   gres=False):
    lay = ResourceLayout.from_gres_names(
        [("gpu", "a100")] if gres else [])
    R = lay.num_dims
    total = np.zeros((n_nodes, R), np.int32)
    total[:, 0] = rng.choice([16, 32, 64], n_nodes) * 256
    total[:, 1] = rng.choice([64, 128, 256], n_nodes) * 1024  # MiB
    total[:, 2] = total[:, 1]
    if gres:
        total[:, 3] = rng.choice([0, 4, 8], n_nodes)
    # some nodes partially used already
    used_frac = rng.uniform(0, 0.5, n_nodes)
    avail = (total * (1 - used_frac[:, None])).astype(np.int32)
    alive = rng.random(n_nodes) > 0.05
    cost = rng.uniform(0, 100, n_nodes).astype(np.float32)

    req = np.zeros((n_jobs, R), np.int32)
    req[:, 0] = rng.choice([1, 2, 4, 8], n_jobs) * 256
    req[:, 1] = rng.choice([1, 4, 16], n_jobs) * 1024
    req[:, 2] = req[:, 1]
    if gres:
        req[:, 3] = rng.choice([0, 0, 1, 2], n_jobs)
    node_num = rng.integers(1, max_nodes + 1, n_jobs).astype(np.int32)
    time_limit = rng.choice([60, 3600, 86400], n_jobs).astype(np.int32)
    # partition membership: node -> one of n_parts; job -> one partition
    node_part = rng.integers(0, n_parts, n_nodes)
    job_part = rng.integers(0, n_parts, n_jobs)
    part_mask = node_part[None, :] == job_part[:, None]
    valid = np.ones(n_jobs, bool)
    return lay, dict(avail=avail, total=total, alive=alive, cost=cost), dict(
        req=req, node_num=node_num, time_limit=time_limit,
        part_mask=part_mask, valid=valid), max_nodes


def run_both(state_d, jobs_d, max_nodes):
    # the canonical constructor rounds float costs into the int32 ledger,
    # exactly as the oracle does
    state = make_cluster_state(state_d["avail"], state_d["total"],
                               state_d["alive"], state_d["cost"])
    jobs = JobBatch(
        req=jnp.asarray(jobs_d["req"]),
        node_num=jnp.asarray(jobs_d["node_num"]),
        time_limit=jnp.asarray(jobs_d["time_limit"]),
        part_mask=jnp.asarray(jobs_d["part_mask"]),
        valid=jnp.asarray(jobs_d["valid"]),
    )
    placements, new_state = solve_greedy(state, jobs, max_nodes=max_nodes)
    o_placed, o_nodes, o_reason, o_avail, o_cost = solve_greedy_oracle(
        state_d["avail"], state_d["total"], state_d["alive"],
        state_d["cost"], jobs_d["req"], jobs_d["node_num"],
        jobs_d["time_limit"], jobs_d["part_mask"], jobs_d["valid"],
        max_nodes)
    return placements, new_state, (o_placed, o_nodes, o_reason, o_avail,
                                   o_cost)


@pytest.mark.parametrize("seed", range(4))
@pytest.mark.parametrize(
    "n_jobs,n_nodes,n_parts,max_nodes,gres",
    [
        (50, 20, 1, 1, False),
        (200, 50, 3, 1, False),
        (100, 30, 1, 4, False),
        (150, 40, 2, 2, True),
    ],
)
def test_parity_random(seed, n_jobs, n_nodes, n_parts, max_nodes, gres):
    rng = np.random.default_rng(seed * 1000 + n_jobs)
    _, state_d, jobs_d, k = random_problem(
        rng, n_jobs, n_nodes, n_parts, max_nodes, gres)
    placements, new_state, oracle = run_both(state_d, jobs_d, k)
    o_placed, o_nodes, o_reason, o_avail, o_cost = oracle

    np.testing.assert_array_equal(np.asarray(placements.placed), o_placed)
    np.testing.assert_array_equal(np.asarray(placements.nodes), o_nodes)
    np.testing.assert_array_equal(np.asarray(placements.reason), o_reason)
    np.testing.assert_array_equal(np.asarray(new_state.avail), o_avail)
    np.testing.assert_allclose(np.asarray(new_state.cost), o_cost,
                               rtol=1e-5)


def test_oversubscription_never_happens():
    rng = np.random.default_rng(7)
    _, state_d, jobs_d, k = random_problem(rng, 500, 10, 1, 1)
    _, new_state, _ = run_both(state_d, jobs_d, k)
    assert np.all(np.asarray(new_state.avail) >= 0)


def test_empty_cluster_places_nothing():
    rng = np.random.default_rng(3)
    _, state_d, jobs_d, k = random_problem(rng, 20, 5, 1, 1)
    state_d["alive"][:] = False
    placements, _, _ = run_both(state_d, jobs_d, k)
    assert not np.asarray(placements.placed).any()
    assert (np.asarray(placements.reason) != REASON_NONE).all()


def test_fifo_order_respected():
    """Earlier (higher-priority) jobs get resources first."""
    lay = ResourceLayout()
    total = np.tile(lay.encode(cpu=4, mem_bytes=8 << 30,
                               memsw_bytes=8 << 30, is_capacity=True),
                    (1, 1))
    state_d = dict(avail=total.copy(), total=total,
                   alive=np.ones(1, bool),
                   cost=np.zeros(1, np.float32))
    req = np.tile(lay.encode(cpu=3, mem_bytes=1 << 30,
                             memsw_bytes=1 << 30), (2, 1))
    jobs_d = dict(req=req, node_num=np.ones(2, np.int32),
                  time_limit=np.full(2, 60, np.int32),
                  part_mask=np.ones((2, 1), bool),
                  valid=np.ones(2, bool))
    placements, _, _ = run_both(state_d, jobs_d, 1)
    placed = np.asarray(placements.placed)
    assert placed[0] and not placed[1]
