"""Licenses (reference LicenseManager.h:46-125) and QoS preemption
(reference TryPreempt_, JobScheduler.cpp:6378-6505)."""

import numpy as np

from cranesched_tpu.craned import SimCluster
from cranesched_tpu.ctld import (
    JobScheduler,
    JobSpec,
    JobStatus,
    MetaContainer,
    PendingReason,
    ResourceSpec,
    SchedulerConfig,
)
from cranesched_tpu.ctld.accounting import (
    Account,
    AccountManager,
    AdminLevel,
    Qos,
    User,
)


def make_cluster(num_nodes=4, cpu=8, config=None, accounts=None):
    meta = MetaContainer()
    for i in range(num_nodes):
        meta.add_node(f"cn{i:02d}",
                      meta.layout.encode(cpu=cpu, mem_bytes=16 << 30,
                                         memsw_bytes=16 << 30,
                                         is_capacity=True))
        meta.craned_up(i)
    sched = JobScheduler(meta, config or SchedulerConfig(backfill=False),
                         accounts=accounts)
    cluster = SimCluster(sched)
    sched.dispatch = cluster.dispatch
    sched.dispatch_terminate = cluster.terminate
    return meta, sched, cluster


def spec(cpu=1.0, runtime=50.0, **kw):
    return JobSpec(res=ResourceSpec(cpu=cpu, mem_bytes=1 << 30,
                                    memsw_bytes=1 << 30),
                   sim_runtime=runtime, **kw)


# ---------------- licenses ----------------

def test_license_legality_at_submit():
    meta, sched, cluster = make_cluster()
    sched.licenses.configure("matlab", 4)
    assert sched.submit(spec(licenses={"nope": 1}), now=0.0) == 0
    assert sched.submit(spec(licenses={"matlab": 5}), now=0.0) == 0
    assert sched.submit(spec(licenses={"matlab": 4}), now=0.0) > 0


def test_license_gating_in_cycle():
    meta, sched, cluster = make_cluster(num_nodes=8)
    sched.licenses.configure("matlab", 3)
    a = sched.submit(spec(licenses={"matlab": 2}, runtime=10.0), now=0.0)
    b = sched.submit(spec(licenses={"matlab": 2}, runtime=10.0), now=0.0)
    started = sched.schedule_cycle(now=0.0)
    assert started == [a]    # only 3 seats: b waits
    assert sched.job_info(b).pending_reason == PendingReason.LICENSE
    assert sched.licenses.licenses["matlab"].in_use == 2
    cluster.advance_to(11.0)
    started = sched.schedule_cycle(now=11.0)
    assert started == [b]    # a's seats freed on completion
    cluster.run_until_drained(start=12.0)
    assert sched.licenses.licenses["matlab"].in_use == 0


def test_license_freed_on_cancel():
    meta, sched, cluster = make_cluster()
    sched.licenses.configure("lic", 1)
    a = sched.submit(spec(licenses={"lic": 1}, runtime=500.0), now=0.0)
    sched.schedule_cycle(now=0.0)
    sched.cancel(a, now=1.0)
    sched.schedule_cycle(now=2.0)
    assert sched.licenses.licenses["lic"].in_use == 0


# ---------------- preemption ----------------

def preempt_setup(mode):
    mgr = AccountManager()
    mgr.users["root"] = User(name="root", admin_level=AdminLevel.ROOT)
    mgr.add_qos("root", Qos(name="low", priority=0))
    mgr.add_qos("root", Qos(name="high", priority=1000,
                            preempt={"low"}))
    mgr.add_account("root", Account(name="hpc",
                                    allowed_qos={"low", "high"},
                                    default_qos="low"))
    mgr.add_user("root", User(name="alice", uid=1), "hpc")
    meta, sched, cluster = make_cluster(
        num_nodes=2, cpu=4,
        config=SchedulerConfig(backfill=False, preempt_mode=mode),
        accounts=mgr)
    return meta, sched, cluster


def hpc_spec(cpu, qos, runtime=500.0, **kw):
    return spec(cpu=cpu, runtime=runtime, user="alice", account="hpc",
                qos=qos, **kw)


def test_preempt_requeue_mode():
    meta, sched, cluster = preempt_setup("requeue")
    lo1 = sched.submit(hpc_spec(4.0, "low"), now=0.0)
    lo2 = sched.submit(hpc_spec(4.0, "low"), now=0.0)
    sched.schedule_cycle(now=0.0)
    assert len(sched.running) == 2   # cluster full of low-qos work

    hi = sched.submit(hpc_spec(4.0, "high", runtime=10.0), now=1.0)
    started = sched.schedule_cycle(now=1.0)
    assert hi in started
    assert sched.job_info(hi).status == JobStatus.RUNNING
    # exactly one victim was evicted and requeued as Preempted
    victims = [j for j in (lo1, lo2)
               if sched.job_info(j).status == JobStatus.PENDING]
    assert len(victims) == 1
    assert sched.job_info(victims[0]).pending_reason == \
        PendingReason.PREEMPTED
    assert sched.job_info(victims[0]).requeue_count == 1
    # everything eventually completes (victim reruns after hi finishes)
    cluster.run_until_drained(start=2.0, max_cycles=5000)
    assert all(j.status == JobStatus.COMPLETED
               for j in sched.history.values())


def test_preempt_cancel_mode():
    meta, sched, cluster = preempt_setup("cancel")
    lo = sched.submit(hpc_spec(4.0, "low"), now=0.0)
    lo2 = sched.submit(hpc_spec(4.0, "low"), now=0.0)
    sched.schedule_cycle(now=0.0)
    hi = sched.submit(hpc_spec(4.0, "high", runtime=10.0), now=1.0)
    started = sched.schedule_cycle(now=1.0)
    assert hi in started
    cancelled = [j for j in (lo, lo2)
                 if sched.job_info(j).status == JobStatus.CANCELLED]
    assert len(cancelled) == 1


def test_no_preemption_without_rights_or_mode():
    # same shape but preempt_mode off: the high job just waits
    meta, sched, cluster = preempt_setup("off")
    lo1 = sched.submit(hpc_spec(4.0, "low"), now=0.0)
    lo2 = sched.submit(hpc_spec(4.0, "low"), now=0.0)
    sched.schedule_cycle(now=0.0)
    hi = sched.submit(hpc_spec(4.0, "high", runtime=10.0), now=1.0)
    assert sched.schedule_cycle(now=1.0) == []
    assert sched.job_info(hi).status == JobStatus.PENDING
    # and low-qos jobs cannot preempt each other
    meta2, sched2, cluster2 = preempt_setup("requeue")
    a = sched2.submit(hpc_spec(4.0, "low"), now=0.0)
    b = sched2.submit(hpc_spec(4.0, "low"), now=0.0)
    sched2.schedule_cycle(now=0.0)
    c = sched2.submit(hpc_spec(4.0, "low"), now=1.0)
    assert sched2.schedule_cycle(now=1.0) == []


def test_preempt_evicts_fewest_lowest_youngest():
    # one node, two 2-cpu low jobs (started at different times); a 2-cpu
    # high job needs only ONE eviction: the youngest low job goes
    mgr = AccountManager()
    mgr.users["root"] = User(name="root", admin_level=AdminLevel.ROOT)
    mgr.add_qos("root", Qos(name="low", priority=0))
    mgr.add_qos("root", Qos(name="high", priority=1000,
                            preempt={"low"}))
    mgr.add_account("root", Account(name="hpc",
                                    allowed_qos={"low", "high"},
                                    default_qos="low"))
    mgr.add_user("root", User(name="alice", uid=1), "hpc")
    meta, sched, cluster = make_cluster(
        num_nodes=1, cpu=4,
        config=SchedulerConfig(backfill=False, preempt_mode="requeue"),
        accounts=mgr)
    older = sched.submit(hpc_spec(2.0, "low"), now=0.0)
    sched.schedule_cycle(now=0.0)
    younger = sched.submit(hpc_spec(2.0, "low"), now=5.0)
    sched.schedule_cycle(now=5.0)
    hi = sched.submit(hpc_spec(2.0, "high", runtime=10.0), now=10.0)
    started = sched.schedule_cycle(now=10.0)
    assert hi in started
    assert sched.job_info(older).status == JobStatus.RUNNING
    assert sched.job_info(younger).status == JobStatus.PENDING


def test_remote_license_sync(tmp_path):
    """Remote licenses reconcile from a sync program (reference
    server-synced LicenseManager, LicenseManager.h:46-125): totals and
    external usage follow the server; this cluster's own seats and
    local licenses never move."""
    from cranesched_tpu.ctld.licenses import LicenseManager, LicenseSyncer

    mgr = LicenseManager()
    mgr.configure("matlab", 10, remote=True)
    mgr.configure("ansys", 4)          # local: the server must not touch
    assert mgr.malloc({"matlab": 3})   # our own seats

    prog = tmp_path / "lmstat.sh"
    prog.write_text("#!/bin/bash\n"
                    "echo '# comment ignored'\n"
                    "echo matlab 16 5\n"
                    "echo ansys 99 99\n"
                    "echo fluent 8 2\n"
                    "echo garbage line_without_numbers x\n")
    prog.chmod(0o755)
    syncer = LicenseSyncer(mgr, str(prog), interval=3600)
    assert syncer.sync_once()

    m = mgr.licenses["matlab"]
    assert (m.total, m.in_use, m.external_used) == (16, 3, 5)
    assert m.free == 8
    a = mgr.licenses["ansys"]          # local license shadows the name
    assert (a.total, a.external_used) == (4, 0)
    f = mgr.licenses["fluent"]         # discovered from the server
    assert f.remote and (f.total, f.external_used) == (8, 2)
    assert f.free == 6

    # availability math includes external usage
    assert not mgr.sufficient({"matlab": 9})
    assert mgr.sufficient({"matlab": 8})

    # a failing sync keeps the last observation
    bad = tmp_path / "bad.sh"
    bad.write_text("#!/bin/bash\nexit 3\n")
    bad.chmod(0o755)
    syncer2 = LicenseSyncer(mgr, str(bad))
    assert not syncer2.sync_once()
    assert syncer2.last_error
    assert mgr.licenses["matlab"].total == 16
