"""Every SchedulerConfig.solver backend yields the same control-plane
cycle.

VERDICT r3 #2: the node-sharded solver must be reachable from
``JobScheduler.schedule_cycle`` (not just a standalone kernel), and its
decisions must be bit-identical to the unsharded path THROUGH the
product: same jobs started, same node assignments, same ledger.  The
same contract covers the Pallas single-kernel path (interpret mode on
the CPU test platform).
"""

import numpy as np
import pytest

from cranesched_tpu.craned.sim import SimCluster
from cranesched_tpu.ctld import (
    JobScheduler,
    JobSpec,
    MetaContainer,
    ResourceSpec,
    SchedulerConfig,
)


def _build(solver: str, num_nodes: int, seed: int = 0):
    meta = MetaContainer()
    rng = np.random.default_rng(seed)
    for i in range(num_nodes):
        part = "gpu" if i % 3 == 0 else "default"
        meta.add_node(
            f"cn{i}",
            meta.layout.encode(cpu=int(rng.integers(8, 33)),
                               mem_bytes=int(rng.integers(16, 65)) << 30,
                               memsw_bytes=64 << 30, is_capacity=True),
            partitions=(part,))
        meta.craned_up(i)
    # a couple of dead nodes exercise the alive mask
    meta.craned_down(1)
    sched = JobScheduler(meta, SchedulerConfig(
        backfill=False, solver=solver, preempt_mode="off"))
    sim = SimCluster(sched)
    sim.wire(sched)
    return sched, sim


def _submit_mixed(sched, num_jobs: int, seed: int = 0):
    rng = np.random.default_rng(seed + 1000)
    ids = []
    for i in range(num_jobs):
        part = "gpu" if rng.random() < 0.3 else "default"
        spec = JobSpec(
            res=ResourceSpec(cpu=float(rng.integers(1, 9)),
                             mem_bytes=int(rng.integers(1, 9)) << 30,
                             memsw_bytes=8 << 30),
            partition=part,
            node_num=int(rng.integers(1, 4)),
            time_limit=float(rng.integers(120, 86400)),
            sim_runtime=1e9)
        ids.append(sched.submit(spec, now=float(i) * 0.001))
    return ids


def _cycle_outcome(solver: str, num_nodes: int, num_jobs: int):
    sched, sim = _build(solver, num_nodes)
    _submit_mixed(sched, num_jobs)
    started = sched.schedule_cycle(now=10.0)
    placement = {jid: sorted(sched.running[jid].node_ids)
                 for jid in started}
    ledger = {nid: n.avail.copy() for nid, n in sched.meta.nodes.items()}
    return started, placement, ledger


@pytest.mark.parametrize("solver", ["sharded", "pallas", "native"])
@pytest.mark.parametrize("num_nodes", [64, 67])
def test_backend_matches_device_through_schedule_cycle(solver,
                                                       num_nodes):
    if solver == "native":
        from cranesched_tpu.utils import native
        if not native.available():
            pytest.skip("native library unavailable")
    ref = _cycle_outcome("device", num_nodes, num_jobs=48)
    got = _cycle_outcome(solver, num_nodes, num_jobs=48)
    assert got[0] == ref[0], "different jobs started"
    assert got[1] == ref[1], "different node assignments"
    for nid in ref[2]:
        np.testing.assert_array_equal(ref[2][nid], got[2][nid])


def test_sharded_uses_the_full_test_mesh():
    """The conftest pins an 8-device CPU platform; the sharded backend
    must actually build its mesh over all of them."""
    import jax
    sched, _ = _build("sharded", 16)
    _submit_mixed(sched, 8)
    sched.schedule_cycle(now=1.0)
    assert sched._mesh is not None
    assert sched._mesh.devices.size == len(jax.devices())
