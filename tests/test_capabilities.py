"""Capability semantics: dependencies, job arrays, reservations,
suspend/resume (reference SURVEY §2.8; PublicDefs.proto:136-159,
Array.h:51-177, NodeDefs.h:83-98, JobManager.h:150-152)."""

import numpy as np

from cranesched_tpu.craned import SimCluster
from cranesched_tpu.ctld import (
    JobScheduler,
    JobSpec,
    JobStatus,
    MetaContainer,
    PendingReason,
    ResourceSpec,
    SchedulerConfig,
)
from cranesched_tpu.ctld.defs import ArraySpec, Dependency, DepType
from cranesched_tpu.ctld.wal import WriteAheadLog


def make_cluster(num_nodes=4, cpu=8, config=None, wal=None):
    meta = MetaContainer()
    for i in range(num_nodes):
        meta.add_node(f"cn{i:02d}",
                      meta.layout.encode(cpu=cpu, mem_bytes=16 << 30,
                                         memsw_bytes=16 << 30,
                                         is_capacity=True))
        meta.craned_up(i)
    sched = JobScheduler(meta, config or SchedulerConfig(backfill=False),
                         wal=wal)
    cluster = SimCluster(sched)
    sched.dispatch = cluster.dispatch
    sched.dispatch_terminate = cluster.terminate
    sched.dispatch_suspend = cluster.suspend
    sched.dispatch_resume = cluster.resume
    return meta, sched, cluster


def spec(cpu=1.0, runtime=50.0, **kw):
    return JobSpec(res=ResourceSpec(cpu=cpu, mem_bytes=1 << 30,
                                    memsw_bytes=1 << 30),
                   sim_runtime=runtime, **kw)


# ---------------- dependencies ----------------

def test_afterok_waits_for_success():
    meta, sched, cluster = make_cluster()
    a = sched.submit(spec(runtime=10.0), now=0.0)
    b = sched.submit(spec(dependencies=(Dependency(a, DepType.AFTER_OK),)),
                     now=0.0)
    started = sched.schedule_cycle(now=0.0)
    assert started == [a]
    assert sched.job_info(b).pending_reason == PendingReason.DEPENDENCY
    cluster.advance_to(11.0)
    started = sched.schedule_cycle(now=11.0)
    assert started == [b]


def test_afterok_never_satisfied_on_failure():
    meta, sched, cluster = make_cluster()
    a = sched.submit(spec(runtime=5.0, sim_exit_code=1), now=0.0)
    b = sched.submit(spec(dependencies=(Dependency(a, DepType.AFTER_OK),)),
                     now=0.0)
    sched.schedule_cycle(now=0.0)
    cluster.advance_to(6.0)
    sched.schedule_cycle(now=6.0)
    assert sched.job_info(a).status == JobStatus.FAILED
    sched.schedule_cycle(now=7.0)
    assert sched.job_info(b).pending_reason == \
        PendingReason.DEPENDENCY_NEVER_SATISFIED


def test_afternotok_fires_on_failure():
    meta, sched, cluster = make_cluster()
    a = sched.submit(spec(runtime=5.0, sim_exit_code=1), now=0.0)
    cleanup = sched.submit(
        spec(dependencies=(Dependency(a, DepType.AFTER_NOT_OK),),
             runtime=5.0), now=0.0)
    sched.schedule_cycle(now=0.0)
    cluster.advance_to(6.0)
    started = sched.schedule_cycle(now=6.0)
    assert started == [cleanup]


def test_after_fires_on_start_with_delay():
    meta, sched, cluster = make_cluster()
    a = sched.submit(spec(runtime=100.0), now=0.0)
    b = sched.submit(
        spec(dependencies=(Dependency(a, DepType.AFTER,
                                      delay_seconds=30.0),)), now=0.0)
    started = sched.schedule_cycle(now=0.0)
    assert started == [a]   # b's edge satisfied at start+30
    assert sched.schedule_cycle(now=10.0) == []
    assert sched.job_info(b).pending_reason == PendingReason.DEPENDENCY
    assert sched.schedule_cycle(now=31.0) == [b]


def test_or_dependencies_any_edge_suffices():
    meta, sched, cluster = make_cluster()
    a = sched.submit(spec(runtime=5.0, sim_exit_code=1), now=0.0)
    b = sched.submit(spec(runtime=200.0), now=0.0)
    c = sched.submit(
        spec(dependencies=(Dependency(a, DepType.AFTER_OK),
                           Dependency(b, DepType.AFTER,
                                      delay_seconds=0.0)),
             deps_is_or=True), now=0.0)
    started = sched.schedule_cycle(now=0.0)
    # b started -> the OR is satisfied even though a will fail
    assert set(started) == {a, b}
    assert sched.schedule_cycle(now=1.0) == [c]


def test_dependency_on_unknown_job_never_satisfied():
    meta, sched, cluster = make_cluster()
    b = sched.submit(
        spec(dependencies=(Dependency(9999, DepType.AFTER_ANY),)),
        now=0.0)
    sched.schedule_cycle(now=1.0)
    assert sched.job_info(b).pending_reason == \
        PendingReason.DEPENDENCY_NEVER_SATISFIED


def test_dependency_on_already_finished_job():
    meta, sched, cluster = make_cluster()
    a = sched.submit(spec(runtime=1.0), now=0.0)
    sched.schedule_cycle(now=0.0)
    cluster.advance_to(2.0)
    sched.schedule_cycle(now=2.0)
    assert sched.job_info(a).status == JobStatus.COMPLETED
    b = sched.submit(spec(dependencies=(Dependency(a, DepType.AFTER_OK),)),
                     now=3.0)
    assert sched.schedule_cycle(now=3.0) == [b]


def test_dependency_survives_crash_after_dependee_finished(tmp_path):
    # B depends on A; A completes; ctld crashes BEFORE B runs.  Recovery
    # must re-derive the edge from A's terminal state in history — not
    # wait forever on an event that already fired.
    path = str(tmp_path / "wal")
    wal = WriteAheadLog(path)
    meta, sched, cluster = make_cluster(wal=wal)
    a = sched.submit(spec(runtime=5.0), now=0.0)
    b = sched.submit(spec(dependencies=(Dependency(a, DepType.AFTER_OK),)),
                     now=0.0)
    sched.schedule_cycle(now=0.0)
    cluster.advance_to(6.0)
    sched.process_status_changes()    # A completes; no placement cycle
    wal.close()

    meta2, sched2, cluster2 = make_cluster()
    sched2.recover(WriteAheadLog.replay(path), now=7.0)
    started = sched2.schedule_cycle(now=7.0)
    assert started == [b]


def test_cancelled_pending_child_finalizes_parent():
    meta, sched, cluster = make_cluster(num_nodes=1, cpu=1)
    parent = sched.submit(
        spec(cpu=1.0, runtime=5.0, array=ArraySpec(start=0, end=1)),
        now=0.0)
    sched.schedule_cycle(now=0.0)   # child 0 materializes and runs
    cluster.advance_to(6.0)
    sched.schedule_cycle(now=6.0)   # child 0 done; child 1 materializes
    sched.schedule_cycle(now=7.0)
    pending_children = [j for j in sched.pending.values()
                        if j.array_parent_id == parent]
    running_children = [j for j in sched.running.values()
                        if j.array_parent_id == parent]
    for c in pending_children + running_children:
        sched.cancel(c.job_id, now=8.0)
    sched.schedule_cycle(now=9.0)
    # the template must reach a terminal state, not linger forever
    p = sched.job_info(parent)
    assert p.status.is_terminal


# ---------------- job arrays ----------------

def test_array_materializes_one_child_per_cycle():
    meta, sched, cluster = make_cluster(num_nodes=8)
    parent = sched.submit(
        spec(runtime=100.0, array=ArraySpec(start=0, end=3)), now=0.0)
    started = sched.schedule_cycle(now=0.0)
    assert len(started) == 1           # one child materialized per cycle
    child = sched.job_info(started[0])
    assert child.array_parent_id == parent
    assert child.array_task_id == 0
    assert child.spec.name.endswith("_0")
    for cyc in range(1, 4):
        started = sched.schedule_cycle(now=float(cyc))
        assert len(started) == 1
    assert sched.schedule_cycle(now=5.0) == []   # all 4 materialized


def test_array_run_limit_percent_n():
    meta, sched, cluster = make_cluster(num_nodes=8)
    sched.submit(spec(runtime=50.0,
                      array=ArraySpec(start=0, end=5, max_concurrent=2)),
                 now=0.0)
    for cyc in range(6):
        sched.schedule_cycle(now=float(cyc))
    # only 2 children may run at once
    assert len(sched.running) == 2
    end = cluster.run_until_drained(start=6.0, max_cycles=5000)
    children = [j for j in sched.history.values()
                if j.array_task_id is not None]
    assert len(children) == 6
    assert all(j.status == JobStatus.COMPLETED for j in children)


def test_array_parent_completes_after_children():
    meta, sched, cluster = make_cluster(num_nodes=8)
    parent = sched.submit(
        spec(runtime=10.0, array=ArraySpec(start=1, end=2)), now=0.0)
    cluster.run_until_drained(start=0.0, max_cycles=1000)
    p = sched.job_info(parent)
    assert p.status == JobStatus.COMPLETED
    assert len(p.array_children) == 2


def test_array_cancel_cancels_remaining():
    meta, sched, cluster = make_cluster(num_nodes=2, cpu=2)
    parent = sched.submit(
        spec(cpu=2.0, runtime=100.0, array=ArraySpec(start=0, end=9)),
        now=0.0)
    sched.schedule_cycle(now=0.0)
    sched.schedule_cycle(now=1.0)   # two children running
    running_children = list(sched.running)
    sched.cancel(parent, now=2.0)
    sched.schedule_cycle(now=3.0)
    p = sched.job_info(parent)
    assert p.status == JobStatus.CANCELLED
    for c in running_children:
        assert sched.job_info(c).status == JobStatus.CANCELLED
    assert not sched.pending and not sched.running


# ---------------- reservations ----------------

def test_reservation_excludes_outside_jobs():
    meta, sched, cluster = make_cluster(num_nodes=4, cpu=8)
    assert meta.create_reservation(
        "maint", "default", ["cn00", "cn01"], start_time=0.0,
        end_time=1000.0) is not None
    # a non-reservation job with a window overlapping the reservation
    # must avoid cn00/cn01
    j = sched.submit(spec(cpu=8.0, time_limit=500), now=0.0)
    sched.schedule_cycle(now=0.0)
    assert sched.job_info(j).node_ids[0] >= 2
    # a reservation job runs inside the carve-out
    r = sched.submit(spec(cpu=8.0, reservation="maint", time_limit=500,
                          runtime=10.0), now=1.0)
    sched.schedule_cycle(now=1.0)
    assert sched.job_info(r).node_ids[0] < 2


def test_reservation_acl():
    meta, sched, cluster = make_cluster(num_nodes=2)
    meta.create_reservation("vip", "default", ["cn00"], 0.0, 1000.0,
                            allowed_accounts=["special"])
    # the default account is not on the reservation's allow list
    assert sched.submit(spec(reservation="vip"), now=0.0) == 0
    # the allowed account submits fine
    assert sched.submit(spec(account="special", reservation="vip"),
                        now=0.0) > 0
    # deny list beats allow list
    meta.reservations["vip"].denied_accounts.add("special")
    assert sched.submit(spec(account="special", reservation="vip"),
                        now=1.0) == 0


def test_reservation_expiry_frees_nodes():
    meta, sched, cluster = make_cluster(num_nodes=1, cpu=8)
    meta.create_reservation("soon", "default", ["cn00"], 0.0, 100.0)
    j = sched.submit(spec(cpu=8.0, time_limit=500, runtime=10.0), now=0.0)
    assert sched.schedule_cycle(now=0.0) == []   # only node reserved
    # after expiry the node frees and the job runs
    assert sched.schedule_cycle(now=100.0) == [j]
    assert "soon" not in meta.reservations


def test_reservation_overlap_rejected():
    meta, sched, cluster = make_cluster(num_nodes=2)
    assert meta.create_reservation("r1", "default", ["cn00"], 0.0,
                                   100.0) is not None
    assert meta.create_reservation("r2", "default", ["cn00"], 50.0,
                                   150.0) is None     # overlapping node
    assert meta.create_reservation("r3", "default", ["cn00"], 100.0,
                                   200.0) is not None  # back-to-back ok
    assert meta.create_reservation("r4", "default", ["cn01"], 0.0,
                                   100.0) is not None  # disjoint node


def test_future_reservation_blocks_overlapping_window_only():
    meta, sched, cluster = make_cluster(num_nodes=1, cpu=8)
    meta.create_reservation("later", "default", ["cn00"], 1000.0, 2000.0)
    # short job finishes before the reservation starts -> allowed
    short = sched.submit(spec(cpu=8.0, time_limit=500, runtime=10.0),
                         now=0.0)
    # long job would run into the reservation -> blocked
    lng = sched.submit(spec(cpu=8.0, time_limit=1500, runtime=10.0),
                       now=0.0)
    started = sched.schedule_cycle(now=0.0)
    assert short in started and lng not in started


# ---------------- suspend / resume ----------------

def test_suspend_resume_credits_time():
    meta, sched, cluster = make_cluster()
    j = sched.submit(spec(runtime=100.0, time_limit=3600), now=0.0)
    sched.schedule_cycle(now=0.0)
    assert sched.suspend(j, now=10.0)
    assert sched.job_info(j).status == JobStatus.SUSPENDED
    # frozen: does not complete at t=100
    cluster.advance_to(150.0)
    sched.schedule_cycle(now=150.0)
    assert sched.job_info(j).status == JobStatus.SUSPENDED
    assert sched.resume(j, now=200.0)
    job = sched.job_info(j)
    assert job.status == JobStatus.RUNNING
    assert job.suspended_total == 190.0
    # completes after the remaining 90s of runtime
    cluster.advance_to(291.0)
    sched.schedule_cycle(now=291.0)
    assert sched.job_info(j).status == JobStatus.COMPLETED
    assert sched.job_info(j).end_time == 290.0


def test_suspended_job_keeps_resources():
    meta, sched, cluster = make_cluster(num_nodes=1, cpu=4)
    a = sched.submit(spec(cpu=4.0, runtime=100.0), now=0.0)
    b = sched.submit(spec(cpu=4.0, runtime=10.0), now=0.0)
    sched.schedule_cycle(now=0.0)
    sched.suspend(a, now=1.0)
    # the freezer keeps memory/cpu allocated: b must NOT start
    assert sched.schedule_cycle(now=2.0) == []
    sched.resume(a, now=3.0)
    cluster.run_until_drained(start=4.0, max_cycles=1000)
    assert sched.job_info(b).status == JobStatus.COMPLETED


def test_suspended_job_recovers_as_suspended(tmp_path):
    path = str(tmp_path / "wal")
    wal = WriteAheadLog(path)
    meta, sched, cluster = make_cluster(wal=wal)
    j = sched.submit(spec(runtime=100.0), now=0.0)
    sched.schedule_cycle(now=0.0)
    sched.suspend(j, now=5.0)
    wal.close()

    meta2, sched2, cluster2 = make_cluster()
    sched2.recover(WriteAheadLog.replay(path), now=6.0)
    job = sched2.job_info(j)
    assert job.status == JobStatus.SUSPENDED
    assert j in sched2.running
    node = meta2.nodes[job.node_ids[0]]
    assert node.avail[0] < node.total[0]   # allocation held
