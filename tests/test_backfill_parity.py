"""Time-axis solver parity + backfill behavior.

Semantics under test (reference: min-over-duration-window fit,
src/CraneCtld/JobScheduler.cpp:6278-6291; earliest-start selection
JobScheduler.h:792-865; in-cycle reservations + "Priority" reason
cpp:6795-6835)."""

import numpy as np
import jax.numpy as jnp
import pytest

from cranesched_tpu.models.solver_time import (
    NO_START,
    TimedJobBatch,
    make_timed_state,
    solve_backfill,
)
from cranesched_tpu.ops.resources import ResourceLayout
from cranesched_tpu.testing.time_oracle import (
    build_time_avail_oracle,
    solve_backfill_oracle,
)

LAY = ResourceLayout()
T = 16


def make_state(avail, total, alive=None, cost=None, run=None,
               num_buckets=T):
    avail = np.asarray(avail)
    n = avail.shape[0]
    alive = np.ones(n, bool) if alive is None else np.asarray(alive)
    cost = (np.zeros(n, np.float32) if cost is None
            else np.asarray(cost, np.float32))
    if run is None:
        run_nodes = np.zeros((0, 1), np.int32)
        run_req = np.zeros((0, avail.shape[1]), np.int32)
        run_end = np.zeros(0, np.int32)
    else:
        run_nodes, run_req, run_end = run
    state = make_timed_state(avail, total, alive, run_nodes, run_req,
                             run_end, num_buckets, cost)
    oracle_ta = build_time_avail_oracle(avail, run_nodes, run_req, run_end,
                                        num_buckets)
    np.testing.assert_array_equal(np.asarray(state.time_avail), oracle_ta)
    return state, oracle_ta, alive, cost


def make_jobs(reqs, node_nums, durs, part_mask=None, valid=None,
              time_limits=None, num_nodes=None):
    J = len(reqs)
    req = np.stack(reqs).astype(np.int32)
    nn = np.asarray(node_nums, np.int32)
    # unit grid (edges=None): 1 bucket == 1 second, so time_limit IS the
    # duration in buckets — the solver derives the window from it
    tl = (np.asarray(time_limits, np.int32) if time_limits is not None
          else np.asarray(durs, np.int32))
    pm = (np.ones((J, num_nodes), bool) if part_mask is None
          else np.asarray(part_mask))
    v = np.ones(J, bool) if valid is None else np.asarray(valid)
    return TimedJobBatch(req=jnp.asarray(req), node_num=jnp.asarray(nn),
                         time_limit=jnp.asarray(tl),
                         part_mask=jnp.asarray(pm),
                         valid=jnp.asarray(v)), (req, nn, tl, pm, v)


def assert_parity(state, oracle_ta, alive, cost, jobs, cols, max_nodes):
    req, nn, tl, pm, v = cols
    placements, new_state = solve_backfill(state, jobs,
                                           max_nodes=max_nodes)
    o_placed, o_start, o_nodes, o_reason, o_ta, o_cost = \
        solve_backfill_oracle(oracle_ta, np.asarray(state.total), alive,
                              cost, req, nn, tl, pm, v, max_nodes)
    np.testing.assert_array_equal(np.asarray(placements.placed), o_placed)
    got_start = np.asarray(placements.start_bucket)
    np.testing.assert_array_equal(np.where(o_placed, got_start, 0),
                                  np.where(o_placed, o_start, 0))
    np.testing.assert_array_equal(np.asarray(placements.nodes), o_nodes)
    np.testing.assert_array_equal(np.asarray(placements.reason), o_reason)
    np.testing.assert_array_equal(np.asarray(new_state.time_avail), o_ta)
    np.testing.assert_allclose(np.asarray(new_state.cost), o_cost,
                               rtol=1e-6)
    return placements


def test_immediate_fit_starts_at_zero():
    total = np.tile(LAY.encode(cpu=8, is_capacity=True), (2, 1))
    state, ota, alive, cost = make_state(total.copy(), total)
    jobs, cols = make_jobs([LAY.encode(cpu=4)], [1], [4], num_nodes=2)
    p = assert_parity(state, ota, alive, cost, jobs, cols, max_nodes=1)
    assert bool(p.placed[0]) and int(p.start_bucket[0]) == 0


def test_blocked_job_reserves_future_start():
    # node fully busy until bucket 5; a blocked job must get start=5
    total = np.tile(LAY.encode(cpu=8, is_capacity=True), (1, 1))
    avail = np.tile(LAY.encode(cpu=0, is_capacity=True), (1, 1))
    run = (np.array([[0]], np.int32),
           np.array([LAY.encode(cpu=8)], np.int32),
           np.array([5], np.int32))
    state, ota, alive, cost = make_state(avail, total, run=run)
    jobs, cols = make_jobs([LAY.encode(cpu=8)], [1], [4], num_nodes=1)
    p = assert_parity(state, ota, alive, cost, jobs, cols, max_nodes=1)
    assert bool(p.placed[0]) and int(p.start_bucket[0]) == 5


def test_backfill_around_blocked_high_priority_job():
    """THE backfill scenario: a short low-priority job may run now because
    it finishes before the blocked high-priority job's reserved start; a
    long one may not."""
    # one node 8 cpu; running job holds 8 cpu until bucket 6
    total = np.tile(LAY.encode(cpu=8, is_capacity=True), (1, 1))
    avail = np.tile(LAY.encode(cpu=0, is_capacity=True), (1, 1))
    run = (np.array([[0]], np.int32),
           np.array([LAY.encode(cpu=8)], np.int32),
           np.array([6], np.int32))
    state, ota, alive, cost = make_state(avail, total, run=run)
    # job0 (high prio): needs 8 cpu -> reserved at bucket 6
    # job1 (short, 4 cpu? no — node has 0 free until 6). Use 2 nodes.
    total = np.tile(LAY.encode(cpu=8, is_capacity=True), (2, 1))
    avail = np.stack([LAY.encode(cpu=0, is_capacity=True),
                      LAY.encode(cpu=8, is_capacity=True)])
    run = (np.array([[0]], np.int32),
           np.array([LAY.encode(cpu=8)], np.int32),
           np.array([6], np.int32))
    state, ota, alive, cost = make_state(avail, total, run=run)
    jobs, cols = make_jobs(
        [LAY.encode(cpu=8), LAY.encode(cpu=8), LAY.encode(cpu=8)],
        [2, 1, 1],        # job0 gang of 2 -> must wait for node0
        [4, 6, 8],        # job1 fits before bucket 6 on node1; job2 not
        num_nodes=2)
    p = assert_parity(state, ota, alive, cost, jobs, cols, max_nodes=2)
    # job0: earliest both nodes free for 4 buckets = bucket 6
    assert int(p.start_bucket[0]) == 6
    # job1: node1 free buckets [0, 6) -> backfills NOW
    assert int(p.start_bucket[1]) == 0
    # job2: needs 8 consecutive buckets on node1 but job0's reservation
    # occupies node1 from bucket 6 -> earliest after job0 ends (bucket 10)
    assert int(p.start_bucket[2]) == 10


def test_reservation_not_violated_by_later_jobs():
    # job0 reserves the future; job1 (same shape) must queue behind it,
    # NOT steal the same window
    total = np.tile(LAY.encode(cpu=4, is_capacity=True), (1, 1))
    avail = np.tile(LAY.encode(cpu=0, is_capacity=True), (1, 1))
    run = (np.array([[0]], np.int32),
           np.array([LAY.encode(cpu=4)], np.int32),
           np.array([2], np.int32))
    state, ota, alive, cost = make_state(avail, total, run=run)
    jobs, cols = make_jobs(
        [LAY.encode(cpu=4), LAY.encode(cpu=4)], [1, 1], [3, 3],
        num_nodes=1)
    p = assert_parity(state, ota, alive, cost, jobs, cols, max_nodes=1)
    assert int(p.start_bucket[0]) == 2
    assert int(p.start_bucket[1]) == 5  # strictly after job0's window


def test_window_longer_than_horizon_uses_steady_state():
    # a job longer than the horizon can still start if the steady state
    # fits (all running jobs released before the horizon)
    total = np.tile(LAY.encode(cpu=4, is_capacity=True), (1, 1))
    state, ota, alive, cost = make_state(total.copy(), total)
    jobs, cols = make_jobs([LAY.encode(cpu=4)], [1], [T + 5],
                           num_nodes=1)
    p = assert_parity(state, ota, alive, cost, jobs, cols, max_nodes=1)
    assert bool(p.placed[0]) and int(p.start_bucket[0]) == 0


def test_unschedulable_in_window_gets_resource_reason():
    # node busy past the horizon -> no start bucket exists
    total = np.tile(LAY.encode(cpu=4, is_capacity=True), (1, 1))
    avail = np.tile(LAY.encode(cpu=0, is_capacity=True), (1, 1))
    run = (np.array([[0]], np.int32),
           np.array([LAY.encode(cpu=4)], np.int32),
           np.array([T + 1], np.int32))   # never frees inside window
    state, ota, alive, cost = make_state(avail, total, run=run)
    jobs, cols = make_jobs([LAY.encode(cpu=4)], [1], [2], num_nodes=1)
    p = assert_parity(state, ota, alive, cost, jobs, cols, max_nodes=1)
    assert not bool(p.placed[0])


def test_gang_needs_simultaneous_window():
    # two nodes free at different times: gang of 2 starts when BOTH free
    total = np.tile(LAY.encode(cpu=4, is_capacity=True), (2, 1))
    avail = np.tile(LAY.encode(cpu=0, is_capacity=True), (2, 1))
    run = (np.array([[0], [1]], np.int32),
           np.array([LAY.encode(cpu=4), LAY.encode(cpu=4)], np.int32),
           np.array([3, 7], np.int32))
    state, ota, alive, cost = make_state(avail, total, run=run)
    jobs, cols = make_jobs([LAY.encode(cpu=4)], [2], [2], num_nodes=2)
    p = assert_parity(state, ota, alive, cost, jobs, cols, max_nodes=2)
    assert int(p.start_bucket[0]) == 7


@pytest.mark.parametrize("seed", range(4))
def test_random_parity(seed):
    rng = np.random.default_rng(seed)
    N, J, M = 12, 24, 10
    total = np.stack([
        LAY.encode(cpu=int(rng.integers(4, 17)),
                   mem_bytes=int(rng.integers(8, 65)) << 30,
                   is_capacity=True) for _ in range(N)])
    # running jobs eat into avail
    run_nodes = rng.integers(0, N, size=(M, 1)).astype(np.int32)
    run_req = np.stack([
        LAY.encode(cpu=int(rng.integers(1, 5)),
                   mem_bytes=int(rng.integers(1, 9)) << 30)
        for _ in range(M)]).astype(np.int32)
    run_end = rng.integers(1, T + 3, size=M).astype(np.int32)
    avail = total.copy()
    for i in range(M):
        avail[run_nodes[i, 0]] -= run_req[i]
    avail = np.maximum(avail, 0)
    alive = rng.random(N) > 0.1
    cost = (rng.random(N) * 5).astype(np.float32)
    state, ota, alive, cost = make_state(
        avail, total, alive, cost, run=(run_nodes, run_req, run_end))
    reqs = [LAY.encode(cpu=int(rng.integers(1, 9)),
                       mem_bytes=int(rng.integers(1, 33)) << 30)
            for _ in range(J)]
    jobs, cols = make_jobs(
        reqs, rng.integers(1, 4, J), rng.integers(1, T + 2, J),
        part_mask=rng.random((J, N)) > 0.15,
        valid=rng.random(J) > 0.05, num_nodes=N)
    assert_parity(state, ota, alive, cost, jobs, cols, max_nodes=4)
    # invariant: no bucket anywhere ever oversubscribed
    placements, new_state = solve_backfill(state, jobs, max_nodes=4)
    assert (np.asarray(new_state.time_avail) >= 0).all()


def test_split_backfill_cycle_protects_reservations():
    """Bounded lookahead (backfill_max_jobs < pending): head jobs get
    full timed semantics; tail jobs are placed against the min-over-
    horizon availability, so they can never steal a head reservation."""
    import numpy as np

    from cranesched_tpu.craned.sim import SimCluster
    from cranesched_tpu.ctld import (
        JobScheduler, JobSpec, MetaContainer, PendingReason,
        ResourceSpec, SchedulerConfig)

    meta = MetaContainer()
    for i in range(2):
        meta.add_node(f"cn{i}", meta.layout.encode(
            cpu=8, mem_bytes=16 << 30, memsw_bytes=16 << 30,
            is_capacity=True))
        meta.craned_up(i)
    sched = JobScheduler(meta, SchedulerConfig(
        backfill=True, backfill_max_jobs=1, time_resolution=60.0,
        time_buckets=16, priority_type="basic"))
    sim = SimCluster(sched)
    sim.wire(sched)

    def spec(cpu, runtime, prio=0, node_num=1):
        return JobSpec(res=ResourceSpec(cpu=cpu, mem_bytes=1 << 30,
                                        memsw_bytes=1 << 30),
                       time_limit=runtime, sim_runtime=runtime,
                       qos_priority=prio, node_num=node_num)

    # cn0: a running job holds 4 cpus for 60s
    blocker = sched.submit(spec(4.0, 60.0), now=0.0)
    assert sched.schedule_cycle(now=0.5) == [blocker]
    # head (1 job): a 2-node whole-cluster gang -> must wait for the
    # blocker, reserving BOTH nodes from bucket 1
    big = sched.submit(spec(8.0, 300.0, node_num=2), now=1.0)
    # tail: fits cn1's CURRENT avail (8 free) but its 600 s run would
    # collide with big's reservation — the split cycle must refuse it
    small = sched.submit(spec(4.0, 600.0), now=1.1)
    started = sched.schedule_cycle(now=2.0)
    assert big not in started              # holds a reservation
    assert small not in started, "tail job stole the reserved window"
    assert sched.pending[big].pending_reason in (
        PendingReason.PRIORITY, PendingReason.RESOURCE)
    # once the blocker finishes, the reservation holder starts first
    sim.advance_to(65.0)
    started2 = sched.schedule_cycle(now=65.0)
    assert big in started2


def test_split_backfill_matches_full_when_uncontended():
    """With plenty of room the split cycle places exactly what the full
    timed solve places."""
    import numpy as np

    from cranesched_tpu.craned.sim import SimCluster
    from cranesched_tpu.ctld import (
        JobScheduler, JobSpec, MetaContainer, ResourceSpec,
        SchedulerConfig)

    def build(bf_max):
        meta = MetaContainer()
        for i in range(8):
            meta.add_node(f"cn{i}", meta.layout.encode(
                cpu=16, mem_bytes=32 << 30, memsw_bytes=32 << 30,
                is_capacity=True))
            meta.craned_up(i)
        sched = JobScheduler(meta, SchedulerConfig(
            backfill=True, backfill_max_jobs=bf_max,
            priority_type="basic"))
        sim = SimCluster(sched)
        sim.wire(sched)
        rng = np.random.default_rng(5)
        for _ in range(24):
            sched.submit(JobSpec(
                res=ResourceSpec(cpu=float(rng.integers(1, 5)),
                                 mem_bytes=1 << 30,
                                 memsw_bytes=1 << 30),
                time_limit=float(rng.integers(60, 600)),
                sim_runtime=1e9), now=0.0)
        return sched

    full = build(bf_max=1000)
    split = build(bf_max=4)
    s_full = full.schedule_cycle(now=1.0)
    s_split = split.schedule_cycle(now=1.0)
    assert set(s_split) == set(s_full)
