"""Craned restart re-adoption (reference Craned.cpp:1345-1449; VERDICT
r3 weak #6): supervisors are separate processes that survive a craned
crash — a restarted craned must reattach to them from its durable step
registry and resume reporting, and must deliver outcomes of steps that
finished while it was down."""

import time

import pytest

from cranesched_tpu.craned.daemon import CranedDaemon, CranedState
from cranesched_tpu.ctld import (
    JobScheduler,
    JobSpec,
    JobStatus,
    MetaContainer,
    ResourceSpec,
    SchedulerConfig,
)
from cranesched_tpu.rpc import serve
from cranesched_tpu.rpc.dispatcher import GrpcDispatcher


@pytest.fixture()
def plane(tmp_path):
    meta = MetaContainer()
    sched = JobScheduler(meta, SchedulerConfig(
        backfill=False, craned_timeout=30.0))
    dispatcher = GrpcDispatcher(sched)
    dispatcher.wire(sched)
    server, port = serve(sched, cycle_interval=0.15,
                         dispatcher=dispatcher)
    daemons = []

    def add_craned(name):
        d = CranedDaemon(name, f"127.0.0.1:{port}", cpu=4.0,
                         mem_bytes=4 << 30, workdir=str(tmp_path),
                         ping_interval=0.5,
                         cgroup_root=str(tmp_path / "nocgroup"))
        d.start()
        daemons.append(d)
        return d

    yield sched, add_craned
    for d in daemons:
        d.stop()
    dispatcher.close()
    server.stop()


def _wait(pred, timeout=25.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.05)
    return False


def test_restarted_craned_readopts_live_supervisor(plane, tmp_path):
    """Kill craned but not the supervisor; the restarted craned adopts
    the live step and the job still completes with its output."""
    sched, add_craned = plane
    d1 = add_craned("rr00")
    assert _wait(lambda: d1.state == CranedState.READY)
    assert _wait(lambda: sched.meta.nodes
                 and sched.meta.node_by_name("rr00").alive)

    out = tmp_path / "radopt_%j.txt"
    jid = sched.submit(JobSpec(
        res=ResourceSpec(cpu=1.0),
        script="sleep 3; echo survived-$CRANE_JOB_ID",
        output_path=str(out), time_limit=60.0), now=time.time())
    assert _wait(lambda: jid in sched.running
                 and sched.running[jid].status == JobStatus.RUNNING,
                 timeout=10.0)
    assert _wait(lambda: (jid, 0) in d1._steps, timeout=10.0), (
        "supervisor never spawned")

    # craned crashes; the supervisor keeps running
    d1.stop(graceful=False, orphan_supervisors=True)
    d2 = add_craned("rr00")
    assert _wait(lambda: d2.state == CranedState.READY)
    assert (jid, 0) in d2._steps, "step not re-adopted"

    assert _wait(lambda: (sched.job_info(jid) or None) is not None
                 and sched.job_info(jid).status.is_terminal,
                 timeout=20.0)
    job = sched.job_info(jid)
    assert job.status == JobStatus.COMPLETED, (
        f"{job.status} exit={job.exit_code}")
    text = (tmp_path / f"radopt_{jid}.txt").read_text()
    assert f"survived-{jid}" in text


def test_restarted_craned_rededucts_alloc_pools(plane, tmp_path):
    """The restarted craned must re-deduct a re-adopted allocation's
    GRES slots and pinned cores from its fresh pools — otherwise the
    next dispatch aliases resources the surviving job still holds
    (review r4: pools reset while kernel pins persist)."""
    sched, add_craned = plane
    d1 = add_craned("rr03")
    d1.gres = {("gpu", ""): 2}
    d1._gres_free = {("gpu", ""): [0, 1]}
    assert _wait(lambda: d1.state == CranedState.READY)

    jid = sched.submit(JobSpec(
        res=ResourceSpec(cpu=2.0),
        script="sleep 300; echo done",
        time_limit=600.0), now=time.time())
    assert _wait(lambda: (jid, 0) in d1._steps, timeout=10.0)
    alloc = d1._allocs[jid]
    # simulate a GRES hold too (the plane meta has no gpu dims, so
    # hold the slots directly and persist — the registry format is
    # what is under test)
    with d1._lock:
        alloc.gres_held = {("gpu", ""): [0]}
        d1._gres_free[("gpu", "")] = [1]
        d1._persist_registry_locked()
    held_cores = alloc.cores_held

    d1.stop(graceful=False, orphan_supervisors=True)
    d2 = CranedDaemon(
        "rr03", d1.ctld_address, cpu=4.0, mem_bytes=4 << 30,
        workdir=str(tmp_path), ping_interval=0.5,
        cgroup_root=str(tmp_path / "nocgroup"),
        gres={("gpu", ""): 2})
    try:
        d2.start()  # _recover_steps runs before registration
        assert jid in d2._allocs
        assert d2._allocs[jid].cores_held == held_cores
        for core in held_cores:
            assert core not in d2._cores_free
        assert d2._gres_free[("gpu", "")] == [1]
        assert _wait(lambda: d2.state == CranedState.READY)
        # cancel through the control plane: the re-adopted step dies
        # and the teardown releases everything back to the pools
        sched.cancel(jid, now=time.time())
        assert _wait(lambda: (j := sched.job_info(jid)) is not None
                     and j.status.is_terminal, timeout=25.0)
        assert _wait(lambda: sorted(d2._cores_free) == list(range(4)),
                     timeout=5.0)
        assert _wait(
            lambda: sorted(d2._gres_free[("gpu", "")]) == [0, 1],
            timeout=5.0)
    finally:
        d2.stop()


def test_outcome_of_step_finished_while_craned_down_is_delivered(
        plane, tmp_path):
    sched, add_craned = plane
    d1 = add_craned("rr01")
    assert _wait(lambda: d1.state == CranedState.READY)
    assert _wait(lambda: sched.meta.nodes
                 and sched.meta.node_by_name("rr01").alive)

    jid = sched.submit(JobSpec(
        res=ResourceSpec(cpu=1.0), script="sleep 1; exit 7",
        time_limit=60.0), now=time.time())
    assert _wait(lambda: jid in sched.running
                 and sched.running[jid].status == JobStatus.RUNNING,
                 timeout=10.0)
    assert _wait(lambda: (jid, 0) in d1._steps, timeout=10.0)
    d1.stop(graceful=False, orphan_supervisors=True)
    time.sleep(2.0)   # the step finishes while no craned is up
    d2 = add_craned("rr01")
    assert _wait(lambda: d2.state == CranedState.READY)
    assert _wait(lambda: (sched.job_info(jid) or None) is not None
                 and sched.job_info(jid).status.is_terminal,
                 timeout=15.0)
    job = sched.job_info(jid)
    assert job.status == JobStatus.FAILED
    assert job.exit_code == 7, "durable report lost its exit code"


def test_readopted_step_still_killable(plane, tmp_path):
    """Control verbs reach a re-adopted supervisor over the FIFO: a
    cancel after restart must actually kill the step."""
    sched, add_craned = plane
    d1 = add_craned("rr02")
    assert _wait(lambda: d1.state == CranedState.READY)
    assert _wait(lambda: sched.meta.nodes
                 and sched.meta.node_by_name("rr02").alive)
    jid = sched.submit(JobSpec(
        res=ResourceSpec(cpu=1.0), script="sleep 600",
        time_limit=900.0), now=time.time())
    assert _wait(lambda: jid in sched.running
                 and sched.running[jid].status == JobStatus.RUNNING,
                 timeout=10.0)
    assert _wait(lambda: (jid, 0) in d1._steps, timeout=10.0)
    d1.stop(graceful=False, orphan_supervisors=True)
    d2 = add_craned("rr02")
    assert _wait(lambda: d2.state == CranedState.READY)
    assert (jid, 0) in d2._steps
    assert sched.cancel(jid, now=time.time())
    assert _wait(lambda: (sched.job_info(jid) or None) is not None
                 and sched.job_info(jid).status.is_terminal,
                 timeout=20.0)
    assert sched.job_info(jid).status == JobStatus.CANCELLED
