"""Benchmark: scheduling decisions/sec of the placement solve on real TPU.

Shapes mirror BASELINE.json's north-star workload (100k pending jobs x 10k
nodes).  The baseline number is the reference's published ">100,000
scheduling decisions per second" (reference README_EN.md:29; see
BASELINE.md) — ``vs_baseline`` is measured decisions/sec divided by that.

Prints exactly ONE JSON line on stdout.

Env overrides: BENCH_JOBS, BENCH_NODES, BENCH_REPEATS,
BENCH_DEVICE_TIMEOUT, BENCH_SCHED_JOBS, BENCH_SCHED_NODES; the device
probe budget is also settable as ``--device-timeout SECONDS``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

BASELINE_DECISIONS_PER_SEC = 100_000.0

# TPU-probe budget: ONE bounded subprocess attempt (an earlier version
# retried until the deadline, so a hanging tunnel charged the timeout
# several times over before the CPU fallback ran)
# raised from 240 (BENCH_r08): the r07 TPU probe timed out mid-init;
# give the runtime's one-time device bring-up a comfortable budget
DEFAULT_DEVICE_TIMEOUT_S = 420.0


def _devices_with_timeout(timeout_s: float) -> dict:
    """TPU acquisition through this environment's tunnel can hang for
    many minutes; probe it ONCE in a subprocess with a hard budget and
    fall back to CPU so the bench always produces a number.

    The probe is the hardened acquisition handshake from
    parallel/acquire.py (env pre-flight -> jax import -> PJRT
    backend init -> device enum, then the compile-warm phases), each
    phase stamped into an fsync'd heartbeat file, so a timeout is never
    bare: the diagnosis names the phase it hung in, carries the child's
    faulthandler stack dump (harvested via SIGUSR1 before the kill),
    and the env pre-flight report (libtpu path, TPU_* vars, chip
    visibility) saying why the plugin had a chance to wedge.  The
    persistent XLA compilation cache under ``profiles/xla_cache/`` is
    enabled in the child, with hit/miss counts reported on success — a
    warm cache takes first_compile off the critical path across runs.

    Returns a diagnosis dict that lands in the output JSON — a CPU
    number must never masquerade as a TPU result without saying why
    (round-2 verdict: record the acquisition failure, don't silently
    benchmark CPU).  The diagnosis is built from THIS run's probe
    outcome, never from a remembered failure mode."""
    from cranesched_tpu.parallel.acquire import acquire_backend

    return acquire_backend(timeout_s, warm=True)


def _build_sched(num_jobs: int, num_nodes: int, wal_dir=None):
    """Cluster + scheduler at a reduced shape, shared by the cycle and
    commit benches; with ``wal_dir`` a REAL fsyncing WAL is attached so
    the traces carry honest durability-barrier counts."""
    from cranesched_tpu.ctld import (
        JobScheduler,
        JobSpec,
        MetaContainer,
        ResourceSpec,
        SchedulerConfig,
    )
    from cranesched_tpu.ctld.wal import WriteAheadLog

    rng = np.random.default_rng(1)
    meta = MetaContainer()
    for i in range(num_nodes):
        meta.add_node(
            f"b{i:05d}",
            meta.layout.encode(cpu=float(rng.integers(32, 129)),
                               mem_bytes=int(rng.integers(64, 513)) << 30,
                               is_capacity=True),
            partitions=(f"p{i % 4}",))
        meta.craned_up(i)
    wal = None
    if wal_dir is not None:
        wal = WriteAheadLog(os.path.join(wal_dir, "bench.wal"),
                            fsync=True)
    sched = JobScheduler(meta, SchedulerConfig(
        schedule_batch_size=num_jobs, backfill_max_jobs=num_jobs,
        solver=os.environ.get("BENCH_SCHED_SOLVER", "auto")),
        wal=wal)

    def submit(k, now):
        for _ in range(k):
            sched.submit(JobSpec(
                res=ResourceSpec(cpu=float(rng.integers(1, 17)),
                                 mem_bytes=int(rng.integers(1, 33)) << 30),
                node_num=int(rng.integers(1, 3)),
                time_limit=int(rng.integers(60, 86400)),
                partition=f"p{rng.integers(0, 4)}"), now=now)

    return sched, submit


def _measure_sched_cycle(num_jobs: int, num_nodes: int) -> dict:
    """One REAL scheduler cycle at a reduced shape: builds a cluster
    spread over four partitions, submits a queue, runs two cycles (the
    first pays jit compiles) and reports the second cycle's phase split
    straight from the cycle trace — the prelude/solve/commit numbers
    the device-resident mask table is accountable for.  A real
    fsyncing WAL (temp dir) is attached so ``wal_fsyncs_per_cycle``
    measures actual durability barriers under group commit."""
    import tempfile

    with tempfile.TemporaryDirectory() as wal_dir:
        sched, submit = _build_sched(num_jobs, num_nodes,
                                     wal_dir=wal_dir)
        # three cycles: the first pays the solver compiles, the second
        # the recompiles from the running-set bucket jumping off zero;
        # topping the queue back up between cycles holds every jit
        # shape constant, so the third cycle is the steady state the
        # trace should describe
        submit(num_jobs, 0.0)
        for c in range(3):
            sched.schedule_cycle(now=float(c + 1))
            submit(num_jobs - len(sched.pending), float(c + 1) + 0.5)
        trace = sched.cycle_trace.snapshot()[-1]
        sched.wal.close()
    out = {k: trace[k] for k in ("solver", "prelude_ms", "solve_ms",
                                 "commit_ms", "dispatch_ms", "total_ms",
                                 "num_streams", "wal_groups",
                                 "recompiles", "device_bytes",
                                 "device_peak_bytes", "device_buffers")
           if k in trace}
    out["jobs"] = num_jobs
    out["nodes"] = num_nodes
    out["wal_fsyncs_per_cycle"] = int(trace.get("wal_fsyncs", 0))
    # device-resident pipeline shape (ctld/resident.py): zero bytes /
    # "off" when the configured backend never acquires the resident
    # state (e.g. the native CPU solver)
    res = getattr(sched, "_resident", None)
    out["resident_mode"] = trace.get(
        "resident", (res.last_mode or "off") if res else "off")
    out["host_to_device_bytes_per_cycle"] = int(
        trace.get("h2d_bytes", 0) or 0)
    out["patch_overlap_share"] = round(
        res.overlap_share() if res else 0.0, 4)
    total = max(float(trace.get("total_ms", 0.0)), 1e-9)
    out["prelude_share"] = round(
        float(trace.get("prelude_ms", 0.0)) / total, 4)
    out["lock_held_share"] = round(
        (float(trace.get("prelude_ms", 0.0))
         + float(trace.get("commit_ms", 0.0))) / total, 4)
    return out


def _measure_commit(num_jobs: int = 10_000,
                    num_nodes: int = 1_024) -> dict:
    """Commit-path microbench: place ``num_jobs`` single-node jobs in
    one cycle against a real fsyncing WAL (temp dir — tmpfs on CI) and
    report the lock-held commit time plus the fsync count.  Group
    commit's acceptance bar: fsyncs per cycle == WAL groups (<= 3),
    not one per started job."""
    import tempfile

    # warm the jit caches on a throwaway scheduler with the SAME shapes
    # so the measured instance's first cycle — an empty cluster taking
    # the full placed wave — is commit-dominated, not compile-dominated
    warm, warm_submit = _build_sched(num_jobs, num_nodes)
    warm_submit(num_jobs, 0.0)
    warm.schedule_cycle(now=1.0)
    with tempfile.TemporaryDirectory() as wal_dir:
        sched, submit = _build_sched(num_jobs, num_nodes,
                                     wal_dir=wal_dir)
        wal = sched.wal
        submit(num_jobs, 0.0)
        f0, g0 = wal.fsync_total, wal.groups_total
        sched.schedule_cycle(now=1.0)
        trace = sched.cycle_trace.snapshot()[-1]
        fsyncs = wal.fsync_total - f0
        groups = wal.groups_total - g0
        wal.close()
    return {
        "jobs": num_jobs, "nodes": num_nodes,
        "placed": int(trace.get("placed", 0)),
        "commit_ms": trace.get("commit_ms"),
        "dispatch_ms": trace.get("dispatch_ms"),
        "total_ms": trace.get("total_ms"),
        "wal_fsyncs": int(fsyncs),
        "wal_groups": int(groups),
        "fsyncs_equal_groups": bool(fsyncs == groups),
        "groups_le_3": bool(groups <= 3),
    }


def _build_churn_sched(num_jobs: int, num_nodes: int,
                       incremental: bool, solver: str = "auto",
                       resident: bool = True, job_trace: bool = True):
    """Small cluster + big queue for the churn scenario: after the
    first cycle fills the nodes, the residual queue is steady-state
    pending — exactly the shape where the incremental prelude should
    scale with dirty rows, not queue depth."""
    from cranesched_tpu.ctld import (
        JobScheduler,
        JobSpec,
        MetaContainer,
        ResourceSpec,
        SchedulerConfig,
    )

    meta = MetaContainer()
    for i in range(num_nodes):
        meta.add_node(
            f"c{i:05d}",
            meta.layout.encode(cpu=64.0, mem_bytes=256 << 30,
                               is_capacity=True),
            partitions=("default",))
        meta.craned_up(i)
    # backfill off: future-start reservations would re-solve every
    # cycle and keep the no-op fingerprint from ever arming — the
    # scenario measures the immediate-fit steady state
    sched = JobScheduler(meta, SchedulerConfig(
        schedule_batch_size=num_jobs, backfill=False,
        incremental=incremental, solver=solver,
        resident_state=resident, job_trace=job_trace))
    rng = np.random.default_rng(42)

    def spec():
        return JobSpec(
            res=ResourceSpec(cpu=float(rng.integers(1, 9)),
                             mem_bytes=int(rng.integers(1, 17)) << 30),
            node_num=1,
            time_limit=int(rng.integers(3600, 86400)))

    return sched, spec, rng


def _measure_churn(num_jobs: int = 100_000, num_nodes: int = 512,
                   churn: float = 0.01, cycles: int = 5) -> dict:
    """The incremental-cycle acceptance scenario (ISSUE 8): a steady
    queue with ``churn`` fraction cancelled+resubmitted per tick, run
    twice — PendingTable path vs ``incremental=False`` full rebuild —
    with identical seeds.  Reports the median prelude per cycle for
    both, the dirty-row counts, and the cost of a fingerprint-hit idle
    tick relative to a full cycle."""

    def run(incremental: bool, solver: str = "auto",
            resident: bool = True, job_trace: bool = True) -> dict:
        sched, spec, rng = _build_churn_sched(num_jobs, num_nodes,
                                              incremental, solver,
                                              resident, job_trace)
        for _ in range(num_jobs):
            sched.submit(spec(), now=0.0)
        started = len(sched.schedule_cycle(now=1.0))  # fills + compiles
        sched.schedule_cycle(now=2.0)  # steady-state (zero-place) shape
        k = max(int(len(sched.pending) * churn), 1)
        preludes, totals, dirty = [], [], []
        h2d_bytes, h2d_rows, dirty_nodes, modes = [], [], [], []
        trace_ms, recompiles, flight_ms = [], [], []
        from cranesched_tpu.obs import introspect
        introspect_s0 = introspect.self_time_s()
        now = 3.0
        for _ in range(cycles):
            pend_ids = list(sched.pending.keys())
            for i in rng.choice(len(pend_ids), size=k, replace=False):
                sched.cancel(int(pend_ids[int(i)]), now=now)
            for _ in range(k):
                sched.submit(spec(), now=now)
            ts0 = (sched.jobtrace.self_time_s
                   if sched.jobtrace is not None else 0.0)
            fs0 = sched.flight.self_time_s
            sched.schedule_cycle(now=now + 0.5)
            flight_ms.append((sched.flight.self_time_s - fs0) * 1e3)
            if sched.jobtrace is not None:
                trace_ms.append(
                    (sched.jobtrace.self_time_s - ts0) * 1e3)
            tr = sched.cycle_trace.snapshot()[-1]
            preludes.append(float(tr.get("prelude_ms", 0.0)))
            totals.append(float(tr.get("total_ms", 0.0)))
            dirty.append(int(tr.get("dirty_jobs") or 0))
            h2d_bytes.append(int(tr.get("h2d_bytes") or 0))
            h2d_rows.append(int(tr.get("h2d_rows") or 0))
            dirty_nodes.append(int(tr.get("dirty_nodes") or 0))
            modes.append(tr.get("resident", "off"))
            recompiles.append(int(tr.get("recompiles") or 0))
            now += 1.0
        introspect_ms = (introspect.self_time_s() - introspect_s0) * 1e3
        # idle tick: the last cycle placed nothing, so the fingerprint
        # is armed on the incremental path; the next no-event cycle
        # should short-circuit before building anything
        skipped0 = sched.stats.get("skipped_cycles", 0)
        t0 = time.perf_counter()
        sched.schedule_cycle(now=now)
        idle_ms = (time.perf_counter() - t0) * 1e3
        res = sched._resident
        return {
            "num_dims": int(sched.meta.layout.num_dims),
            "first_cycle_started": started,
            "prelude_ms": round(float(np.median(preludes)), 3),
            "total_ms": round(float(np.median(totals)), 3),
            "dirty_rows": int(np.median(dirty)),
            "dirty_nodes": int(np.median(dirty_nodes)),
            "h2d_bytes_per_cycle": int(np.median(h2d_bytes)),
            "h2d_rows_per_cycle": int(np.median(h2d_rows)),
            "resident_modes": modes,
            "full_rebuilds": int(res.full_rebuilds),
            "patch_cycles": int(res.patch_cycles),
            "ledger_cycles": int(res.ledger_cycles),
            "patch_overlap_share": round(res.overlap_share(), 4),
            "idle_tick_ms": round(idle_ms, 3),
            "skipped_cycles": (sched.stats.get("skipped_cycles", 0)
                               - skipped0),
            "trace_ms": round(float(np.median(trace_ms)), 4)
            if trace_ms else 0.0,
            "flight_ms": round(float(np.median(flight_ms)), 4)
            if flight_ms else 0.0,
            "recompiles": recompiles,
            "introspect_ms": round(introspect_ms, 4),
        }

    # persistent XLA compilation cache (ISSUE 16): route this process's
    # compiles through profiles/xla_cache/ and report the hit rate —
    # warm runs of the same bench shapes should hit, proving the cache
    # the TPU probe relies on actually works across processes
    from cranesched_tpu.obs.flight import (
        enable_xla_cache, xla_cache_stats)
    xla_enabled = enable_xla_cache()
    xla0 = xla_cache_stats()

    inc = run(True)
    base = run(False)
    # tracing-overhead leg (ISSUE 12): the in-cycle stamp cost (fresh
    # eligible/placed/dispatched edges on the churned k jobs) must
    # stay <= 2% of the churn cycle.  The share is the recorder's own
    # accumulated self-time inside schedule_cycle over the cycle wall
    # time — a direct measurement; differencing whole trace-on/off
    # runs at this shape just reads scheduler jitter (observed both
    # signs at up to 20% on identical seeds).  A trace-off leg still
    # runs as the jitter-bounded sanity context.
    tr_off = run(True, job_trace=False)
    on_ms = max(inc["total_ms"], 1e-9)
    tracing = {
        "cycle_ms_trace_on": inc["total_ms"],
        "cycle_ms_trace_off": tr_off["total_ms"],
        "trace_ms_per_cycle": inc["trace_ms"],
        "trace_overhead_share": round(inc["trace_ms"] / on_ms, 4),
    }
    tracing["overhead_ok"] = bool(
        tracing["trace_overhead_share"] <= 0.02)
    # flight-recorder leg (ISSUE 16): the always-on phase ring stamps
    # ~6 entries per cycle inside schedule_cycle — its accumulated
    # self-time must stay <= 1% of the churn cycle wall time (same
    # direct self-time measurement as the tracing leg).  The XLA cache
    # stats ride along so tier1_perf can assert the hit rate is
    # reported (and a warm second run shows hits > 0).
    xla1 = xla_cache_stats()
    flight = {
        "flight_ms_per_cycle": inc["flight_ms"],
        "flight_overhead_share": round(inc["flight_ms"] / on_ms, 4),
        "xla_cache": {
            "enabled": bool(xla_enabled),
            "dir": xla1["dir"],
            "hits": xla1["hits"] - xla0["hits"],
            "misses": xla1["misses"] - xla0["misses"],
            "entries": xla1["entries"],
            "hit_rate": xla1["hit_rate"],
            "error": xla1["error"],
        },
    }
    flight["overhead_ok"] = bool(
        flight["flight_overhead_share"] <= 0.01)
    # introspection-plane leg (ISSUE 14): warm churn cycles must pay
    # ZERO fresh jit compiles (the bucketed-padding contract, now
    # measured rather than assumed), and the observer probes + device
    # memory sampling must cost <= 2% of the cycle.  Same direct
    # self-time measurement as the tracing leg, same jitter rationale.
    steady_ms = max(inc["total_ms"] * cycles, 1e-9)
    introspection = {
        "recompiles_per_cycle": inc["recompiles"],
        "zero_steady_recompiles": bool(
            all(r == 0 for r in inc["recompiles"])),
        "introspect_ms_total": inc["introspect_ms"],
        "introspect_overhead_share": round(
            inc["introspect_ms"] / steady_ms, 4),
    }
    introspection["overhead_ok"] = bool(
        introspection["introspect_overhead_share"] <= 0.02)
    # resident-state acceptance legs (ISSUE 11): same seed/event stream
    # on the device scan solver, resident patching vs per-cycle rebuild
    res_on = run(True, solver="device", resident=True)
    res_off = run(True, solver="device", resident=False)
    full_ms = max(inc["total_ms"], 1e-9)
    from cranesched_tpu.ctld.resident import (
        full_state_bytes, padded_rows, patch_row_bytes)
    num_dims = res_on["num_dims"]
    steady = res_on["resident_modes"]
    # BENCH_r10 anomaly (ISSUE 17): every steady churn cycle here has
    # an EMPTY delta — nothing places in steady state, so no node row
    # is dirtied and the only H2D traffic is the time-dependent [N]
    # cost ledger (exactly 4*N bytes).  Those cycles used to report
    # mode "patch", which read as patch traffic with dirty_nodes=0 and
    # a speedup of ~1.0 against a bound derived from a phantom dirty
    # row.  They now report mode "ledger", and an all-ledger steady
    # state is held to the EXACT ledger size instead of the padded
    # dirty-row formula.
    ledger_only = bool(steady and all(m == "ledger" for m in steady))
    if ledger_only:
        bound = 4 * num_nodes
    else:
        # dirty-rows bound: the rows the delta snapshot itself re-read
        # this cycle (trace dirty_nodes) plus the full [N] cost seed —
        # a silent full-rebuild regression blows straight past it
        bound = (padded_rows(max(res_on["dirty_nodes"], 1), num_nodes)
                 * patch_row_bytes(num_dims) + 4 * num_nodes)
    resident = {
        "cycle_ms": res_on["total_ms"],
        "rebuild_cycle_ms": res_off["total_ms"],
        "speedup_vs_rebuild": round(
            res_off["total_ms"] / max(res_on["total_ms"], 1e-9), 2),
        "h2d_bytes_per_cycle": res_on["h2d_bytes_per_cycle"],
        "h2d_rows_per_cycle": res_on["h2d_rows_per_cycle"],
        "dirty_nodes": res_on["dirty_nodes"],
        "dirty_bound_bytes": int(bound),
        "full_state_bytes": int(
            full_state_bytes(num_nodes, num_dims)),
        # "no steady cycle fell back to a rebuild" — ledger counts:
        # it ships strictly less than a patch
        "steady_state_patch": bool(
            steady and all(m in ("patch", "ledger") for m in steady)),
        "steady_state_ledger_only": ledger_only,
        "steady_state_modes": {
            m: steady.count(m) for m in sorted(set(steady))},
        "full_rebuilds": res_on["full_rebuilds"],
        "patch_cycles": res_on["patch_cycles"],
        "ledger_cycles": res_on["ledger_cycles"],
        "patch_overlap_share": res_on["patch_overlap_share"],
        "placements_match": bool(
            res_on["first_cycle_started"]
            == res_off["first_cycle_started"]
            == inc["first_cycle_started"]),
    }
    return {
        "jobs": num_jobs, "nodes": num_nodes, "churn": churn,
        "cycles": cycles,
        "incremental": inc, "full_rebuild": base,
        "resident": resident, "tracing": tracing,
        "introspection": introspection, "flight": flight,
        # same seed + same event stream: identical first-wave placement
        # is the in-bench parity check (the real oracle lives in
        # tests/test_delta_cycle.py)
        "placements_match": bool(inc["first_cycle_started"]
                                 == base["first_cycle_started"]),
        "prelude_speedup": round(
            base["prelude_ms"] / max(inc["prelude_ms"], 1e-9), 2),
        "idle_tick_share": round(inc["idle_tick_ms"] / full_ms, 4),
        "idle_skipped": bool(inc["skipped_cycles"] >= 1),
    }


def _build_gang_sched(num_jobs: int, num_nodes: int, block: int):
    """Gang-heavy cluster + scheduler for the topology scenario; the
    same seeded queue is replayed with and without a topology so the
    cycle-time delta is apples to apples.  ``block=0`` = no topology."""
    from cranesched_tpu.ctld import (
        JobScheduler,
        JobSpec,
        MetaContainer,
        ResourceSpec,
        SchedulerConfig,
    )

    meta = MetaContainer()
    for i in range(num_nodes):
        meta.add_node(
            f"t{i:05d}",
            meta.layout.encode(cpu=64.0, mem_bytes=256 << 30,
                               is_capacity=True),
            partitions=("default",))
        meta.craned_up(i)
    if block:
        from cranesched_tpu.topo.model import Topology
        meta.set_topology(Topology.uniform_blocks(num_nodes, block))
    # solver="device": the base run must use the device scan (the same
    # solver family solve_greedy_topo extends) — comparing the topo scan
    # against the native C++ treap would measure backend choice, not the
    # cost of the topology restriction
    sched = JobScheduler(meta, SchedulerConfig(
        schedule_batch_size=num_jobs, backfill=False,
        max_nodes_per_job=8, solver="device"))
    rng = np.random.default_rng(7)

    def submit(k, now):
        for _ in range(k):
            sched.submit(JobSpec(
                res=ResourceSpec(cpu=float(rng.integers(1, 9)),
                                 mem_bytes=int(rng.integers(1, 17)) << 30),
                node_num=int(rng.integers(2, 9)),
                time_limit=int(rng.integers(60, 3600))), now=now)

    return sched, submit


def _measure_topology(num_jobs: int = 256, num_nodes: int = 512,
                      block: int = 64) -> dict:
    """Topology overhead + locality: the same gang-heavy queue solved
    with and without a generated block topology.  Reports the
    intra-block placement rate and the topo solve's cycle/solve-time
    ratio vs the plain solve (acceptance: <= 1.05)."""

    def run(with_topo):
        sched, submit = _build_gang_sched(
            num_jobs, num_nodes, block if with_topo else 0)
        submit(num_jobs, 0.0)
        traces = []
        for c in range(10):
            sched.schedule_cycle(now=float(c + 1))
            submit(num_jobs - len(sched.pending), float(c + 1) + 0.5)
            traces.append(sched.cycle_trace.snapshot()[-1])
        steady = traces[5:]   # first cycles pay the jit compiles
        # min over the steady cycles: the least noise-contaminated
        # sample — cycle walls here are ~15 ms, well inside OS jitter
        return sched, {
            "solver": steady[-1].get("solver"),
            "solve_ms": float(min(
                t.get("solve_ms", 0.0) for t in steady)),
            "total_ms": float(min(
                t.get("total_ms", 0.0) for t in steady)),
        }

    base_sched, base = run(False)
    topo_sched, topo = run(True)
    in_block = int(topo_sched.stats.get("topo_in_block_total", 0))
    cross = int(topo_sched.stats.get("topo_cross_block_total", 0))
    gangs = max(in_block + cross, 1)
    return {
        "jobs": num_jobs, "nodes": num_nodes, "block": block,
        "base": base, "topo": topo,
        "intra_block_rate": round(in_block / gangs, 4),
        "cross_block_gangs": cross,
        "solve_overhead": round(
            topo["solve_ms"] / max(base["solve_ms"], 1e-9), 3),
        "cycle_overhead": round(
            topo["total_ms"] / max(base["total_ms"], 1e-9), 3),
    }


# one controller shard in its own PROCESS: the federated submit-
# throughput comparison must measure real parallelism, and in-process
# shards would share one GIL.  The script serves a full shard (sim node
# plane + background cycles, so queries run against a concurrent solve)
# and prints READY when bound.
_SHARD_SERVER_SRC = r"""
import json, sys, time
cfg = json.loads(sys.argv[1])
from cranesched_tpu.craned.sim import SimCluster
from cranesched_tpu.ctld import JobScheduler, MetaContainer, \
    SchedulerConfig
from cranesched_tpu.fed.shardmap import ShardMap
from cranesched_tpu.rpc.server import serve
meta = MetaContainer()
nid = 0
for part in sorted(cfg["partitions"]):
    for i in range(cfg["partitions"][part]):
        meta.add_node("%s-%s-n%04d" % (cfg["name"], part, i),
                      meta.layout.encode(cpu=16.0, mem_bytes=64 << 30,
                                         memsw_bytes=64 << 30,
                                         is_capacity=True),
                      partitions=(part,))
        meta.craned_up(nid)
        nid += 1
sched = JobScheduler(meta, SchedulerConfig(backfill=False))
sim = SimCluster(sched)
sim.wire(sched)
# boot-time jit warmup: pre-trace the priority model for every queue
# bucket the storm will cross, so no XLA compile ever runs under the
# server lock mid-measurement (see JobScheduler.warm_jit_buckets)
sched.warm_jit_buckets(cfg.get("warm_pending", 8192),
                       max_running=16 * nid)
shard_map = (ShardMap.from_doc(cfg["shards"])
             if cfg.get("shards") else None)
server, port = serve(sched, sim=sim,
                     address="127.0.0.1:%d" % cfg["port"],
                     cycle_interval=cfg.get("cycle_interval", 0.05),
                     shard_name=cfg["name"], shard_map=shard_map)
print("READY", port, flush=True)
while True:
    time.sleep(1)
"""

# query-latency measurer in its OWN process: inside the storming bench
# process the reader thread shares the GIL with protobuf-serializing
# submit threads, which inflates measured latency ~100x with artifacts
# that are the bench client's, not the server's.  Runs until a line
# arrives on stdin, then prints the sample list as JSON.
_QUERY_CLIENT_SRC = r"""
import json, sys, threading, time
from cranesched_tpu.rpc.client import CtldClient
cli = CtldClient(sys.argv[1], timeout=60.0)
stop = threading.Event()
threading.Thread(target=lambda: (sys.stdin.readline(), stop.set()),
                 daemon=True).start()
lat = []
while not stop.is_set():
    t0 = time.perf_counter()
    cli.query_job_summary()
    lat.append((time.perf_counter() - t0) * 1e3)
print(json.dumps(lat), flush=True)
cli.close()
"""


def _measure_federation(n_specs: int = 4_000,
                        nodes_per_part: int = 32) -> dict:
    """Federated control-plane numbers (ISSUE 15): submit throughput of
    two subprocess shards over disjoint partitions vs ONE controller
    over the union, query p99 under the concurrent background solve,
    and the arbiter's share of placements from the closed-loop
    federation sim.

    Method: each controller is measured IN ISOLATION (one server
    process alive at a time, identical client concurrency and identical
    total submitted work per scenario), and the federated figure is the
    sum of the per-shard isolated rates.  Shards share no state and
    deploy on separate controller hosts, so the aggregate is additive
    by construction; running both shard processes concurrently on this
    host would only time-slice its cores and measure the bench box, not
    the control plane."""
    import socket
    import subprocess
    import threading

    from cranesched_tpu.rpc import crane_pb2 as pb
    from cranesched_tpu.rpc.client import CtldClient

    env = dict(os.environ, JAX_PLATFORMS="cpu")

    def free_port():
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        return port

    def spawn(cfg):
        proc = subprocess.Popen(
            [sys.executable, "-c", _SHARD_SERVER_SRC, json.dumps(cfg)],
            env=env, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL)
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            if proc.poll() is not None:
                raise RuntimeError(
                    f"shard {cfg['name']} died rc={proc.returncode}")
            try:
                socket.create_connection(
                    ("127.0.0.1", cfg["port"]), timeout=0.5).close()
                return proc
            except OSError:
                time.sleep(0.1)
        proc.kill()
        raise RuntimeError(f"shard {cfg['name']} never bound")

    def spec(partition):
        return pb.JobSpec(
            res=pb.ResourceSpec(cpu=1.0, mem_bytes=1 << 30,
                                memsw_bytes=1 << 30),
            partition=partition, sim_runtime=5.0)

    def storm(address, partitions, total_specs):
        """Saturate ONE live controller: one submit thread per entry in
        `partitions` (identical client concurrency in every scenario),
        plus a dedicated query-client PROCESS measuring read latency
        while the server is solving + absorbing writes.  A warmup wave
        runs first so the background cycles pay their jit compiles
        before the clock starts."""
        per = total_specs // len(partitions)
        walls = [0.0] * len(partitions)
        accepted = [0] * len(partitions)

        warm = CtldClient(address, timeout=60.0)
        for _ in range(per // 250):
            # full-volume warmup: walk the pending queue through every
            # padding bucket the measured storm will hit
            warm.submit_many([spec(partitions[0])] * 250)
            time.sleep(0.4)
        time.sleep(4.0)  # background cycles compile + settle
        warm.close()

        qp = subprocess.Popen(
            [sys.executable, "-c", _QUERY_CLIENT_SRC, address],
            env=env, stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.DEVNULL, text=True)
        time.sleep(0.3)  # let the query client connect + start looping

        def submit(i, partition):
            cli = CtldClient(address, timeout=60.0)
            batch = [spec(partition)] * 250
            t0 = time.perf_counter()
            for _ in range(per // 250):
                replies = cli.submit_many(batch).replies
                accepted[i] += sum(1 for r in replies if r.job_id)
            walls[i] = time.perf_counter() - t0
            cli.close()

        threads = [threading.Thread(target=submit, args=(i, p))
                   for i, p in enumerate(partitions)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        qp.stdin.write("stop\n")
        qp.stdin.flush()
        q_lat = json.loads(qp.stdout.readline() or "[]")
        qp.wait(timeout=30)
        total = sum(accepted)
        wall = max(walls)
        lat = np.asarray(q_lat) if q_lat else np.zeros(1)
        return {
            "jobs_accepted": total,
            "wall_s": round(wall, 3),
            "submits_per_s": round(total / wall, 1) if wall else 0.0,
            "query_samples": len(q_lat),
            "query_p50_ms": round(float(np.percentile(lat, 50)), 2),
            "query_p99_ms": round(float(np.percentile(lat, 99)), 2),
        }

    ports = {"solo": free_port(), "east": free_port(),
             "west": free_port()}
    shards_doc = [
        {"name": "east", "partitions": ["batch"],
         "address": f"127.0.0.1:{ports['east']}", "followers": []},
        {"name": "west", "partitions": ["gpu"],
         "address": f"127.0.0.1:{ports['west']}", "followers": []},
    ]

    def isolated(cfg, partitions, total_specs):
        proc = spawn(cfg)
        try:
            return storm(f"127.0.0.1:{cfg['port']}", partitions,
                         total_specs)
        finally:
            proc.kill()
            proc.wait()

    # one controller over the union of partitions, saturated by two
    # submit threads (one per partition)
    single = isolated(
        {"name": "solo", "port": ports["solo"],
         "partitions": {"batch": nodes_per_part,
                        "gpu": nodes_per_part}},
        ["batch", "gpu"], n_specs)
    # each shard alone, same two-thread saturation, half the work each
    # (the same n_specs total lands on the federation)
    east = isolated(
        {"name": "east", "port": ports["east"],
         "partitions": {"batch": nodes_per_part},
         "shards": shards_doc},
        ["batch", "batch"], n_specs // 2)
    west = isolated(
        {"name": "west", "port": ports["west"],
         "partitions": {"gpu": nodes_per_part},
         "shards": shards_doc},
        ["gpu", "gpu"], n_specs // 2)
    federated = {
        "jobs_accepted": east["jobs_accepted"] + west["jobs_accepted"],
        "submits_per_s": round(
            east["submits_per_s"] + west["submits_per_s"], 1),
        "query_p50_ms": max(east["query_p50_ms"],
                            west["query_p50_ms"]),
        "query_p99_ms": max(east["query_p99_ms"],
                            west["query_p99_ms"]),
        "per_shard": {"east": east, "west": west},
    }

    # arbiter share from the closed-loop federation sim (the same drill
    # REPLAY_r07 records, including the mid-storm shard SIGKILL)
    from cranesched_tpu.replay import replay_federation
    drill = replay_federation(0.1, np.random.default_rng(0))
    locals_finished = drill["jobs_submitted"] - drill["gangs"]
    members = drill["jobs_finished"] - locals_finished
    speedup = (federated["submits_per_s"]
               / max(single["submits_per_s"], 1e-9))
    return {
        "specs_per_scenario": n_specs,
        "nodes_per_partition": nodes_per_part,
        "method": "each controller saturated in isolation (one server "
                  "process at a time, identical client concurrency); "
                  "federated = sum of per-shard isolated rates — "
                  "shards share nothing and run on separate hosts",
        "single": single,
        "federated": federated,
        "submit_speedup": round(speedup, 2),
        "speedup_ge_2x": bool(speedup >= 2.0),
        "query_p99_lt_50ms": bool(
            federated["query_p99_ms"] < 50.0),
        "arbiter": {
            "gang_share_submitted": drill["gang_share"],
            "commits": drill["gang_commits"],
            "aborts": drill["gang_aborts"],
            "members_placed": members,
            "arbiter_share_of_placements": round(
                members / max(drill["jobs_finished"], 1), 3),
            "ledger_ok": drill["ok"],
        },
    }


# one rank of the multi-host solve: loads the shared problem, slices
# its node slab, bootstraps a ProcessMesh over the parent's rendezvous
# (CRANE_RENDEZVOUS/_TOKEN env), runs the solve twice — cold (pays the
# two per-shape jit compiles) and warm on a rebuilt slab state — and
# reports the warm wall plus its fence share from the mesh histogram.
_MULTIHOST_CHILD_SRC = r"""
import json, os, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import jax.numpy as jnp
from cranesched_tpu.models.solver import make_cluster_state
from cranesched_tpu.parallel.distributed import (
    _MET_FENCE, bootstrap_process_mesh, solve_greedy_sharded_classes_mp)

rank = int(os.environ["CRANE_MP_RANK"])
nprocs = int(os.environ["CRANE_MP_NPROCS"])
pb = dict(np.load(sys.argv[1]))
max_nodes = int(pb.pop("max_nodes"))
n = pb["avail"].shape[0]
slab = n // nprocs
lo, hi = rank * slab, (rank + 1) * slab
jargs = [jnp.asarray(pb[k]) for k in
         ("req", "node_num", "time_limit", "valid", "job_class")]
cmask = jnp.asarray(pb["class_masks"][:, lo:hi])


def slab_state():
    return make_cluster_state(pb["avail"][lo:hi], pb["total"][lo:hi],
                              pb["alive"][lo:hi], pb["cost"][lo:hi])


def fence_totals():
    return [sum(v[k] for v in _MET_FENCE.snapshot().values())
            for k in ("count", "sum")]


pmesh = bootstrap_process_mesh(rank, nprocs, slab)
t0 = time.perf_counter()
p, s = solve_greedy_sharded_classes_mp(
    pmesh, slab_state(), *jargs, cmask, max_nodes=max_nodes)
jax.block_until_ready((p.placed, s.avail))
cold_s = time.perf_counter() - t0
f0 = fence_totals()
t0 = time.perf_counter()
p, s = solve_greedy_sharded_classes_mp(
    pmesh, slab_state(), *jargs, cmask, max_nodes=max_nodes)
jax.block_until_ready((p.placed, s.avail))
warm_s = time.perf_counter() - t0
f1 = fence_totals()
print(json.dumps({
    "rank": rank, "mesh": pmesh.describe(),
    "cold_s": round(cold_s, 4), "warm_s": round(warm_s, 4),
    "fence_count": int(f1[0] - f0[0]),
    "fence_s": round(f1[1] - f0[1], 4),
    "placed": np.asarray(p.placed).tolist(),
    "nodes": np.asarray(p.nodes).tolist(),
    "reason": np.asarray(p.reason).tolist(),
    "avail": np.asarray(s.avail).tolist()}), flush=True)
pmesh.close()
"""


def _measure_multihost(num_jobs: int = 512, num_nodes: int = 256,
                       num_classes: int = 8, nprocs: int = 2,
                       local_devices: int = 4,
                       max_nodes: int = 2) -> dict:
    """First multi-host solve number (ISSUE 17): ``nprocs`` real OS
    processes — separate jax runtimes with ``local_devices`` forced
    host devices each, node slabs split between them — bootstrap over
    a RendezvousServer and run the hierarchical
    ``solve_greedy_sharded_classes_mp``.  The CI stand-in for a pod
    slice: same code path, CPU devices, rendezvous on loopback.

    Reports the warm per-cycle wall (max over ranks — the solve
    completes when the slowest rank does), its host-fence share, and
    asserts bit-exact parity against the single-process
    ``solve_greedy_sharded_classes`` oracle computed in THIS process."""
    import subprocess
    import tempfile

    import jax
    import jax.numpy as jnp

    from cranesched_tpu.models.solver import make_cluster_state
    from cranesched_tpu.ops.resources import ResourceLayout
    from cranesched_tpu.parallel.sharded import (
        make_node_mesh,
        shard_cluster_state,
        solve_greedy_sharded_classes,
    )
    from cranesched_tpu.rpc.rendezvous import RendezvousServer

    num_nodes -= num_nodes % (nprocs * local_devices)  # even slabs
    rng = np.random.default_rng(17)
    lay = ResourceLayout()
    total = np.stack([
        lay.encode(cpu=int(rng.integers(8, 65)),
                   mem_bytes=int(rng.integers(16, 257)) << 30,
                   is_capacity=True)
        for _ in range(num_nodes)])
    used = np.stack([
        lay.encode(cpu=float(rng.integers(0, 8)),
                   mem_bytes=int(rng.integers(0, 8)) << 30)
        for _ in range(num_nodes)])
    pb = dict(
        avail=total - np.minimum(used, total), total=total,
        alive=rng.random(num_nodes) >= 0.05,
        cost=rng.random(num_nodes).astype(np.float32) * 10,
        req=np.stack([
            lay.encode(cpu=float(rng.integers(1, 17)),
                       mem_bytes=int(rng.integers(1, 33)) << 30)
            for _ in range(num_jobs)]),
        node_num=rng.integers(1, max_nodes + 1,
                              size=num_jobs).astype(np.int32),
        time_limit=rng.integers(60, 86400,
                                size=num_jobs).astype(np.int32),
        valid=(rng.random(num_jobs) > 0.05),
        job_class=rng.integers(0, num_classes,
                               size=num_jobs).astype(np.int32),
        class_masks=(rng.random((num_classes, num_nodes)) > 0.25))

    # single-process oracle over this process's own device mesh
    mesh = make_node_mesh()
    state = make_cluster_state(pb["avail"], pb["total"], pb["alive"],
                               pb["cost"])
    p_ref, s_ref = solve_greedy_sharded_classes(
        shard_cluster_state(state, mesh), jnp.asarray(pb["req"]),
        jnp.asarray(pb["node_num"]), jnp.asarray(pb["time_limit"]),
        jnp.asarray(pb["valid"]), jnp.asarray(pb["job_class"]),
        jnp.asarray(pb["class_masks"]), mesh, max_nodes=max_nodes)
    jax.block_until_ready(p_ref.placed)

    server = RendezvousServer(token="bench-mh", nranks=nprocs, epoch=1)
    port = server.start("127.0.0.1:0")
    procs, outs = [], []
    with tempfile.TemporaryDirectory() as tmp:
        npz = os.path.join(tmp, "problem.npz")
        np.savez(npz, max_nodes=max_nodes, **pb)
        try:
            for rank in range(nprocs):
                env = dict(os.environ)
                # the children must never inherit an injected hang or
                # a TPU library discovery — they are the CPU stand-in
                env.pop("BENCH_ACQUIRE_INJECT_HANG", None)
                env.pop("BENCH_PROBE_INJECT_HANG", None)
                env.update({
                    "JAX_PLATFORMS": "cpu",
                    "XLA_FLAGS": ("--xla_force_host_platform_device_"
                                  f"count={local_devices}"),
                    "CRANE_RENDEZVOUS": f"127.0.0.1:{port}",
                    "CRANE_RENDEZVOUS_TOKEN": "bench-mh",
                    "CRANE_MP_RANK": str(rank),
                    "CRANE_MP_NPROCS": str(nprocs),
                })
                procs.append(subprocess.Popen(
                    [sys.executable, "-c", _MULTIHOST_CHILD_SRC, npz],
                    stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                    text=True, env=env))
            for p in procs:
                out, err = p.communicate(timeout=540)
                if p.returncode != 0:
                    raise RuntimeError(
                        f"multihost rank died rc={p.returncode}: "
                        f"{err[-2000:]}")
                outs.append(json.loads(out.strip().splitlines()[-1]))
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
            server.stop()

    # every rank computes the same global placements; they must match
    # the single-process oracle bit for bit (the acceptance contract —
    # a multi-host number for a DIFFERENT schedule would be worthless)
    ref_placed = np.asarray(p_ref.placed).tolist()
    ref_nodes = np.asarray(p_ref.nodes).tolist()
    ref_reason = np.asarray(p_ref.reason).tolist()
    parity = all(o["placed"] == ref_placed and o["nodes"] == ref_nodes
                 and o["reason"] == ref_reason for o in outs)
    avail_mp = np.concatenate([np.asarray(o["avail"]) for o in outs])
    parity = parity and bool(
        np.array_equal(avail_mp, np.asarray(s_ref.avail)))
    if not parity:
        raise AssertionError(
            "multi-host solve diverged from the single-process oracle")
    warm = max(o["warm_s"] for o in outs)
    fence_s = max(o["fence_s"] for o in outs)
    return {
        "jobs": num_jobs, "nodes": num_nodes, "classes": num_classes,
        "max_nodes": max_nodes,
        "procs": nprocs, "local_devices_per_proc": local_devices,
        "mesh": outs[0]["mesh"],
        "cold_cycle_s": round(max(o["cold_s"] for o in outs), 4),
        "warm_cycle_s": round(warm, 4),
        "decisions_per_sec": round(num_jobs / max(warm, 1e-9), 1),
        "fence_count_per_cycle": outs[0]["fence_count"],
        "fence_seconds_per_cycle": round(fence_s, 4),
        "fence_share": round(fence_s / max(warm, 1e-9), 4),
        "parity_with_single_process": True,
        "placed": int(sum(ref_placed)),
        "note": "CPU pod-slice stand-in: real processes + rendezvous "
                "fences on loopback; on TPU the same path rides ICI "
                "inside slabs and the host fence between hosts",
    }


def _measure_rebalance(n_jobs: int = 600,
                       nodes_per_part: int = 24) -> dict:
    """Elastic-federation handoff numbers (ISSUE 18): seal a LOADED
    partition on one shard mid-storm and hand it to another — measure
    the submit-outage window (seal→flip, the only interval where the
    partition refuses work), the per-job handoff cost of the
    seal→export→import→flip→commit sequence, and one gossip round of
    the cluster-wide UsageBook.  The run audits itself BY NAME across
    shards afterwards: a handoff that loses or doubles a single job is
    a failed measurement, not a slow one."""
    import shutil
    import tempfile

    from cranesched_tpu.ctld.defs import JobSpec, ResourceSpec
    from cranesched_tpu.fed.sim import FederatedCluster
    from cranesched_tpu.fed.usage import GlobalLimits

    tmp = tempfile.mkdtemp(prefix="crane-rebalance-bench-")
    try:
        fc = FederatedCluster(
            {"east": {"batch": nodes_per_part,
                      "debug": max(nodes_per_part // 4, 2)},
             "west": {"gpu": nodes_per_part}},
            cpu=16.0, mem_gb=64, wal_dir=tmp,
            global_limits=GlobalLimits(
                max_submit_jobs_per_user=n_jobs * 2),
            publish_slack=32)
        # waves sized to the publish slack with a gossip pump between:
        # the conservative gate only admits `slack` unpublished jobs,
        # so a pumpless bulk submit would measure the throttle, not
        # the handoff
        names = []
        wave, i = 32, 0
        while i < n_jobs:
            for _ in range(min(wave, n_jobs - i)):
                name = f"rb{i:05d}"
                i += 1
                _, jid = fc.submit(JobSpec(
                    name=name, user="bench", partition="batch",
                    res=ResourceSpec(cpu=2.0, mem_bytes=2 << 30,
                                     memsw_bytes=2 << 30),
                    sim_runtime=20.0))
                if jid:
                    names.append(name)
            fc.tick()
            fc.pump_usage(fc.now)
        running = len(fc.shards["east"].scheduler.running)

        t0 = time.perf_counter()
        res = fc.migrate("batch", "west")
        handoff_s = time.perf_counter() - t0
        moved = res["jobs_imported"]

        t0 = time.perf_counter()
        docs = fc.pump_usage(fc.now)
        gossip_ms = (time.perf_counter() - t0) * 1e3

        # post-flip the map must route new work to the adopter
        routed_to = fc.shard_map.shard_for_partition("batch")
        _, jid = fc.submit(JobSpec(
            name="rb-post-flip", user="bench", partition="batch",
            res=ResourceSpec(cpu=1.0, mem_bytes=1 << 30,
                             memsw_bytes=1 << 30), sim_runtime=1.0))
        if jid:
            names.append("rb-post-flip")
        # drain with the gossip pump running — the conservative gate
        # needs fresh summaries to keep admitting run slots (in a real
        # federation the pump is a background loop, never paused)
        for _ in range(100_000):
            fc.tick()
            fc.pump_usage(fc.now)
            if all(s.drained() for s in fc.shards.values()):
                break
        audit = fc.ledger_by_name(names)
        ok = (res["committed"] and audit["lost"] == []
              and audit["doubled"] == [] and audit["still_live"] == []
              and routed_to == "west" and jid > 0)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return {
        "jobs_submitted": len(names),
        "running_at_handoff": running,
        "jobs_moved": moved,
        "handoff_s": round(handoff_s, 4),
        "per_job_ms": round(handoff_s / max(moved, 1) * 1e3, 3),
        "submit_outage_s": round(handoff_s, 4),
        "map_epoch": fc.shard_map.epoch,
        "usage_gossip_docs": docs,
        "usage_gossip_ms": round(gossip_ms, 3),
        "audit": {k: (len(v) if isinstance(v, list) else v)
                  for k, v in audit.items()},
        "exactly_once": ok,
        "note": "in-process two-shard drill over real WALs; the "
                "outage window IS the handoff (flip precedes commit, "
                "so clients see at most one sealed-partition retry)",
    }


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument(
        "--device-timeout", type=float, default=float(
            os.environ.get("BENCH_DEVICE_TIMEOUT",
                           DEFAULT_DEVICE_TIMEOUT_S)),
        help="TPU device-probe budget in seconds before the CPU "
             "fallback (env BENCH_DEVICE_TIMEOUT)")
    ap.add_argument(
        "--topology", action="store_true",
        default=bool(os.environ.get("BENCH_TOPOLOGY")),
        help="also run the topology scenario: gang-heavy queue with and "
             "without a generated block topology (intra-block placement "
             "rate + cycle-time delta; env BENCH_TOPOLOGY)")
    ap.add_argument(
        "--federation", action="store_true",
        default=bool(os.environ.get("BENCH_FEDERATION")),
        help="also run the federated control-plane scenario: 2-shard "
             "subprocess submit throughput vs one controller, query "
             "p99 under concurrent solve, and the arbiter's placement "
             "share (env BENCH_FEDERATION; shape via BENCH_FED_SPECS/"
             "BENCH_FED_NODES)")
    ap.add_argument(
        "--multihost", action="store_true",
        default=bool(os.environ.get("BENCH_MULTIHOST")),
        help="also run the multi-host solve scenario: 2 real processes "
             "(forced CPU host devices) bootstrap a ProcessMesh over a "
             "rendezvous and run the hierarchical sharded-classes "
             "solve, bit-exact vs the single-process oracle (env "
             "BENCH_MULTIHOST; shape via BENCH_MH_JOBS/BENCH_MH_NODES/"
             "BENCH_MH_PROCS/BENCH_MH_DEVICES)")
    ap.add_argument(
        "--rebalance", action="store_true",
        default=bool(os.environ.get("BENCH_REBALANCE")),
        help="also run the elastic-federation scenario: migrate a "
             "loaded partition between two live shards mid-storm and "
             "report the handoff latency (submit-outage window), "
             "per-job move cost, usage-gossip round time, and the "
             "exactly-once-by-name audit (env BENCH_REBALANCE; shape "
             "via BENCH_RB_JOBS/BENCH_RB_NODES)")
    ap.add_argument(
        "--churn", action="store_true",
        default=bool(os.environ.get("BENCH_CHURN")),
        help="also run the incremental-cycle churn scenario: steady 1%% "
             "queue churn, PendingTable vs full-rebuild prelude, plus "
             "the fingerprint-hit idle-tick cost (env BENCH_CHURN; "
             "shape via BENCH_CHURN_JOBS/BENCH_CHURN_NODES)")
    args = ap.parse_args()

    num_jobs = int(os.environ.get("BENCH_JOBS", 100_000))
    num_nodes = int(os.environ.get("BENCH_NODES", 10_000))
    repeats = int(os.environ.get("BENCH_REPEATS", 3))

    acquisition = {"acquired": True, "attempts": [],
                   "note": "JAX_PLATFORMS=cpu was pre-set"}
    if os.environ.get("JAX_PLATFORMS", "") != "cpu":
        # probe whenever CPU isn't already forced: auto-detection with an
        # unset JAX_PLATFORMS can hang on the TPU tunnel just as well
        acquisition = _devices_with_timeout(args.device_timeout)

    import jax
    import jax.numpy as jnp

    from cranesched_tpu.models.solver import (
        JobBatch,
        make_cluster_state,
        solve_greedy,
    )
    from cranesched_tpu.ops.resources import ResourceLayout

    dev = jax.devices()[0]
    rng = np.random.default_rng(0)
    lay = ResourceLayout()

    total = np.stack([
        lay.encode(cpu=int(rng.integers(32, 129)),
                   mem_bytes=int(rng.integers(64, 513)) << 30,
                   is_capacity=True)
        for _ in range(num_nodes)
    ])
    state = make_cluster_state(total.copy(), total,
                               rng.random(num_nodes) > 0.02,
                               rng.random(num_nodes).astype(np.float32))

    req = np.stack([
        lay.encode(cpu=float(rng.integers(1, 17)),
                   mem_bytes=int(rng.integers(1, 33)) << 30)
        for _ in range(num_jobs)
    ])
    # Partition eligibility computed on device (a [J, N] host transfer at
    # this scale would dominate; real cycles also build it device-side).
    node_part = jnp.asarray(rng.integers(0, 4, num_nodes), jnp.int32)
    job_part = jnp.asarray(rng.integers(0, 4, num_jobs), jnp.int32)
    part_mask = job_part[:, None] == node_part[None, :]

    jobs = JobBatch(
        req=jnp.asarray(req),
        node_num=jnp.asarray(rng.integers(1, 3, num_jobs), jnp.int32),
        time_limit=jnp.asarray(rng.integers(60, 86400, num_jobs), jnp.int32),
        part_mask=part_mask,
        valid=jnp.ones(num_jobs, bool))

    state = jax.device_put(state, dev)
    jobs = jax.device_put(jobs, dev)

    from cranesched_tpu.models.pallas_solver import (
        plan_streams,
        solve_greedy_pallas,
        solve_greedy_pallas_auto,
    )
    from cranesched_tpu.models.speculative import solve_blocked
    from cranesched_tpu.utils import native

    node_part_np = np.asarray(node_part)
    job_part_np = np.asarray(job_part)
    node_num_np = np.asarray(jobs.node_num)
    time_limit_np = np.asarray(jobs.time_limit)
    alive_np = np.asarray(state.alive).astype(np.uint8)
    avail_np = np.asarray(state.avail)
    cost_np = np.asarray(state.cost)

    def run_native():
        out = native.solve_greedy_native(
            avail_np, total, alive_np, cost_np, req, node_num_np,
            time_limit_np, np.ones(num_jobs, np.uint8), max_nodes=2,
            job_part=job_part_np, node_part=node_part_np)
        if out is None:
            raise RuntimeError("native library unavailable")

        class _P:  # placements shim matching the device solvers' shape
            placed = out[0]
        return _P, None

    # the Pallas path takes eligibility as (job_class, class_masks)
    # instead of the dense [J, N] part_mask (see models/pallas_solver.py)
    class_masks = jnp.asarray(
        np.stack([np.asarray(node_part) == c for c in range(4)]))

    def run_pallas():
        return solve_greedy_pallas(
            state, jobs.req, jobs.node_num, jobs.time_limit, jobs.valid,
            job_part, class_masks, max_nodes=2)

    # the production routing: class-disjoint partitions decompose into
    # S independent streams (models/pallas_solver.plan_streams), solved
    # by one multi-stream kernel.  The bench workload's 4 partitions are
    # disjoint by construction, so this is the streamed kernel.  No
    # donation here: the timing loop reuses `state` across repeats
    # (the scheduler donates, because it rebuilds state every cycle).
    stream_plan = plan_streams(job_part_np, np.asarray(class_masks))
    bench_streams = stream_plan[1] if stream_plan is not None else 1

    def run_pallas_stream():
        return solve_greedy_pallas_auto(
            state, jobs.req, jobs.node_num, jobs.time_limit, jobs.valid,
            job_part, class_masks, max_nodes=2, plan=stream_plan)

    def run_backfill():
        # the time-axis solve at the same shape (VERDICT r3 #5: a
        # recorded backfill number).  T=64 buckets, idle-cluster map
        # (the map build is measured separately in real cycles).
        from cranesched_tpu.models.solver_time import (
            TimeGrid, TimedJobBatch, make_timed_state, solve_backfill)
        tstate = make_timed_state(
            state.avail, state.total, state.alive,
            np.zeros((0, 1), np.int32), np.zeros((0, req.shape[1]),
                                                 np.int32),
            np.zeros(0, np.int32), num_buckets=64, cost=state.cost)
        tjobs = TimedJobBatch(
            req=jobs.req, node_num=jobs.node_num,
            time_limit=jobs.time_limit,
            part_mask=jobs.part_mask, valid=jobs.valid)
        return solve_backfill(tstate, tjobs,
                              edges=TimeGrid(64, 60.0).jnp_edges,
                              max_nodes=2, group=8)

    def run_backfill_split(bf_max=1024):
        # the production composition for time-axis cycles at scale
        # (SchedulerConfig.backfill_max_jobs): full timed solve for the
        # top bf_max priority jobs, Pallas immediate solve for the tail
        # against the min-over-horizon availability (reservation-safe)
        from cranesched_tpu.models.solver_time import (
            TimeGrid, TimedJobBatch, make_timed_state, solve_backfill)
        tstate = make_timed_state(
            state.avail, state.total, state.alive,
            np.zeros((0, 1), np.int32), np.zeros((0, req.shape[1]),
                                                 np.int32),
            np.zeros(0, np.int32), num_buckets=64, cost=state.cost)
        head = jax.tree.map(lambda x: x[:bf_max], jobs)
        tjobs = TimedJobBatch(
            req=head.req, node_num=head.node_num,
            time_limit=head.time_limit,
            part_mask=head.part_mask, valid=head.valid)
        tp, tstate = solve_backfill(tstate, tjobs,
                                    edges=TimeGrid(64, 60.0).jnp_edges,
                                    max_nodes=2, group=8)
        min_avail = jnp.min(tstate.time_avail, axis=1)
        tail_state = state.replace(avail=min_avail, cost=tstate.cost)
        p2, _ = solve_greedy_pallas(
            tail_state, jobs.req[bf_max:], jobs.node_num[bf_max:],
            jobs.time_limit[bf_max:], jobs.valid[bf_max:],
            job_part[bf_max:], class_masks, max_nodes=2)

        class _P:
            placed = jnp.concatenate([tp.placed, p2.placed])
        return _P, None

    solvers = {
        "greedy": lambda: solve_greedy(state, jobs, max_nodes=2),
        "blocked": lambda: solve_blocked(state, jobs, max_nodes=2,
                                         block_size=128),
        "backfill": run_backfill,
    }
    if dev.platform == "tpu":
        solvers["backfill_split"] = run_backfill_split
    if dev.platform == "tpu":
        # the single-kernel Pallas solve is the TPU hot path (VMEM-
        # resident cluster state, no per-job dispatch); it does not
        # lower on the CPU backend (interpret mode is test-only)
        solvers["pallas"] = run_pallas
        solvers["pallas-stream"] = run_pallas_stream
    if dev.platform == "cpu" and native.available():
        # the host C++ solver only competes for the headline number when
        # the measurement is a CPU measurement anyway — on a real TPU the
        # reported decisions/sec must be a device number
        solvers["native"] = run_native
    which = os.environ.get("BENCH_SOLVER", "auto")
    if which != "auto":
        if which not in solvers:
            print(json.dumps({"error": f"BENCH_SOLVER={which!r} invalid; "
                              f"use one of {['auto', *solvers]}"}))
            return 1
        solvers = {which: solvers[which]}
    elif num_jobs * num_nodes > 10_000_000:
        # the blocked solver's parallel validation measured ~17 s/cycle
        # on TPU and worse on CPU at the north-star shape (BENCH_r04);
        # auto mode drops it there, and the time-axis backfill (~T x
        # heavier per step) runs only when explicitly requested
        # (BENCH_SOLVER=backfill — recorded in BENCH_r04_backfill.json).
        # The scan greedy stays as the reference point against the
        # Pallas kernel.
        solvers.pop("blocked", None)
        solvers.pop("backfill", None)
        solvers.pop("backfill_split", None)

    results = {}
    placed_by = {}
    for name, fn in solvers.items():
        def ready(pl):
            if hasattr(pl.placed, "block_until_ready"):
                pl.placed.block_until_ready()

        p, _ = fn()           # warmup / compile
        ready(p)
        times = []
        budget = time.perf_counter() + 120.0  # per-solver wall budget
        for _ in range(repeats):
            t0 = time.perf_counter()
            p, _ = fn()
            ready(p)
            times.append(time.perf_counter() - t0)
            if time.perf_counter() > budget:
                break
        results[name] = float(np.median(times))
        placed_by[name] = int(np.asarray(p.placed).sum())

    best = min(results, key=results.get)
    placements_placed = placed_by[best]
    cycle_s = results[best]
    decisions_per_sec = num_jobs / cycle_s

    # full-cycle phase split from the production scheduler's own trace
    # (prelude = drains + sort + batch build; the factored mask table
    # keeps it a small share of the cycle)
    sched_cycle = None
    sj = int(os.environ.get("BENCH_SCHED_JOBS", 4_096))
    sn = int(os.environ.get("BENCH_SCHED_NODES", 512))
    if sj > 0 and sn > 0:
        try:
            sched_cycle = _measure_sched_cycle(sj, sn)
        except Exception as exc:  # never sink the headline number
            sched_cycle = {"error": f"{type(exc).__name__}: {exc}"}

    # commit-path microbench: group-commit fsync amortization +
    # lock-held commit time on a place-everything cycle
    commit_bench = None
    cj = int(os.environ.get("BENCH_COMMIT_JOBS", 10_000))
    cn = int(os.environ.get("BENCH_COMMIT_NODES", 1_024))
    if cj > 0 and cn > 0:
        try:
            commit_bench = _measure_commit(cj, cn)
        except Exception as exc:
            commit_bench = {"error": f"{type(exc).__name__}: {exc}"}

    topo_bench = None
    if args.topology:
        try:
            topo_bench = _measure_topology()
        except Exception as exc:
            topo_bench = {"error": f"{type(exc).__name__}: {exc}"}

    fed_bench = None
    if args.federation:
        try:
            # 32 nodes/partition keeps the storm queue-saturated like
            # the north-star shape (jobs >> free slots); with more
            # slots than specs every wave places instantly and the
            # scenario measures commit churn, not scheduling ingest
            fed_bench = _measure_federation(
                n_specs=int(os.environ.get("BENCH_FED_SPECS", 4_000)),
                nodes_per_part=int(os.environ.get("BENCH_FED_NODES",
                                                  32)))
        except Exception as exc:
            fed_bench = {"error": f"{type(exc).__name__}: {exc}"}

    mh_bench = None
    if args.multihost:
        try:
            mh_bench = _measure_multihost(
                num_jobs=int(os.environ.get("BENCH_MH_JOBS", 512)),
                num_nodes=int(os.environ.get("BENCH_MH_NODES", 256)),
                num_classes=int(os.environ.get("BENCH_MH_CLASSES", 8)),
                nprocs=int(os.environ.get("BENCH_MH_PROCS", 2)),
                local_devices=int(os.environ.get("BENCH_MH_DEVICES",
                                                 4)))
        except Exception as exc:
            mh_bench = {"error": f"{type(exc).__name__}: {exc}"}

    rb_bench = None
    if args.rebalance:
        try:
            rb_bench = _measure_rebalance(
                n_jobs=int(os.environ.get("BENCH_RB_JOBS", 600)),
                nodes_per_part=int(os.environ.get("BENCH_RB_NODES",
                                                  24)))
        except Exception as exc:
            rb_bench = {"error": f"{type(exc).__name__}: {exc}"}

    churn_bench = None
    if args.churn:
        try:
            churn_bench = _measure_churn(
                num_jobs=int(os.environ.get("BENCH_CHURN_JOBS",
                                            100_000)),
                num_nodes=int(os.environ.get("BENCH_CHURN_NODES", 512)),
                churn=float(os.environ.get("BENCH_CHURN_RATE", 0.01)),
                cycles=int(os.environ.get("BENCH_CHURN_CYCLES", 5)))
        except Exception as exc:
            churn_bench = {"error": f"{type(exc).__name__}: {exc}"}

    print(json.dumps({
        "metric": "decisions_per_sec",
        "value": round(decisions_per_sec, 1),
        "unit": "decisions/s",
        "vs_baseline": round(decisions_per_sec / BASELINE_DECISIONS_PER_SEC,
                             3),
        "detail": {
            "jobs": num_jobs, "nodes": num_nodes,
            "solver": best,
            "cycle_seconds_by_solver": {k: round(v, 4)
                                        for k, v in results.items()},
            "placed": placements_placed,
            "num_streams": bench_streams,
            "sched_cycle": sched_cycle,
            "commit": commit_bench,
            "topology": topo_bench,
            "churn": churn_bench,
            "federation": fed_bench,
            "multihost": mh_bench,
            "rebalance": rb_bench,
            "device": str(dev), "repeats": repeats,
            "device_acquisition": acquisition,
        },
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
